"""Filter trees → AllowList masks.

Reference: entities/filters (operator tree) evaluated by
adapters/repos/db/inverted/searcher.go into a roaring-bitmap AllowList
(helpers/allow_list.go:19) that the vector search consumes as a mask.
"""

from weaviate_tpu.filters.filters import Filter, Operator, compute_allow_mask

__all__ = ["Filter", "Operator", "compute_allow_mask"]
