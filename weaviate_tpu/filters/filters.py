"""Filter operator tree and its evaluation against a shard's inverted index.

Reference: entities/filters/filters.go (Operator enum, Clause tree) +
adapters/repos/db/inverted/searcher.go (per-clause row readers producing
roaring bitmaps, merged with and/or/not set algebra).

The TPU twist: the result is a dense bool mask over the shard's doc-id
space, shipped to the device and ANDed with the live-slot mask *inside*
the top-k scan (SURVEY §7 hard part #3) — filtering costs one vector
`logical_and`, not a host-side candidate loop.
"""

from __future__ import annotations

import fnmatch
import math
import re
from dataclasses import dataclass, field

import numpy as np

from weaviate_tpu.schema.config import DataType
from weaviate_tpu.text.inverted import InvertedIndex, parse_date
from weaviate_tpu.text.tokenizer import tokenize


class Operator:
    AND = "And"
    OR = "Or"
    NOT = "Not"  # children negated against the full doc set
    EQUAL = "Equal"
    NOT_EQUAL = "NotEqual"
    GREATER_THAN = "GreaterThan"
    GREATER_THAN_EQUAL = "GreaterThanEqual"
    LESS_THAN = "LessThan"
    LESS_THAN_EQUAL = "LessThanEqual"
    LIKE = "Like"
    IS_NULL = "IsNull"
    CONTAINS_ANY = "ContainsAny"
    CONTAINS_ALL = "ContainsAll"
    WITHIN_GEO_RANGE = "WithinGeoRange"

    LOGICAL = {AND, OR, NOT}
    RANGE = {GREATER_THAN, GREATER_THAN_EQUAL, LESS_THAN, LESS_THAN_EQUAL}


@dataclass
class Filter:
    operator: str
    path: str | list[str] | None = None  # property name (list = ref path, last = prop)
    value: object = None
    operands: list["Filter"] = field(default_factory=list)

    # convenience constructors ------------------------------------------------

    @classmethod
    def and_(cls, *operands):
        return cls(Operator.AND, operands=list(operands))

    @classmethod
    def or_(cls, *operands):
        return cls(Operator.OR, operands=list(operands))

    @classmethod
    def not_(cls, *operands):
        return cls(Operator.NOT, operands=list(operands))

    @classmethod
    def where(cls, path: str, operator: str, value):
        return cls(operator, path=path, value=value)

    @property
    def prop(self) -> str:
        if isinstance(self.path, (list, tuple)):
            return self.path[-1]
        return self.path

    # serialization (REST/gRPC where-filter payloads) --------------------------

    def to_dict(self) -> dict:
        d = {"operator": self.operator}
        if self.path is not None:
            d["path"] = self.path if isinstance(self.path, list) else [self.path]
        if self.value is not None:
            d["value"] = self.value
        if self.operands:
            d["operands"] = [o.to_dict() for o in self.operands]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Filter":
        # accept both our canonical form and weaviate REST's typed values
        # (valueText/valueInt/valueNumber/valueBoolean/valueDate/valueGeoRange)
        value = d.get("value")
        if value is None:
            for key in ("valueText", "valueString", "valueInt", "valueNumber",
                        "valueBoolean", "valueDate", "valueGeoRange",
                        "valueTextArray", "valueIntArray", "valueNumberArray",
                        "valueBooleanArray"):
                if key in d:
                    value = d[key]
                    break
        return cls(
            operator=d["operator"],
            path=d.get("path"),
            value=value,
            operands=[cls.from_dict(o) for o in d.get("operands", [])],
        )


def _geo_distance_m(lat1, lon1, lat2, lon2):
    """Haversine distance in meters (vectorized). Reference:
    distancer/geo_spatial.go uses the same great-circle formula."""
    rlat1, rlon1, rlat2, rlon2 = (np.radians(x) for x in (lat1, lon1, lat2, lon2))
    a = (np.sin((rlat2 - rlat1) / 2) ** 2
         + np.cos(rlat1) * np.cos(rlat2) * np.sin((rlon2 - rlon1) / 2) ** 2)
    return 2 * 6_371_000.0 * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


def compute_allow_mask(f: Filter, inv: InvertedIndex, size: int) -> np.ndarray:
    """Evaluate a filter tree to a bool mask over [0, size) doc ids."""
    return _eval(f, inv, size)


def _full(inv: InvertedIndex, size: int) -> np.ndarray:
    return _from_ids(inv.all_docs(), size)


def _from_ids(ids, size: int) -> np.ndarray:
    """Sorted id array (or any iterable of ids) -> dense bool mask."""
    mask = np.zeros(size, dtype=bool)
    arr = np.asarray(ids, dtype=np.int64) if not isinstance(ids, np.ndarray) \
        else ids.astype(np.int64, copy=False)
    if len(arr):
        arr = arr[arr < size]
        if len(arr):
            mask[arr] = True
    return mask


def _eval(f: Filter, inv: InvertedIndex, size: int) -> np.ndarray:
    op = f.operator
    if op in Operator.LOGICAL:
        if not f.operands:
            raise ValueError(f"{op} filter requires operands")
        masks = [_eval(o, inv, size) for o in f.operands]
        if op == Operator.AND:
            out = masks[0]
            for m in masks[1:]:
                out = out & m
            return out
        if op == Operator.OR:
            out = masks[0]
            for m in masks[1:]:
                out = out | m
            return out
        # NOT: docs not matching any operand
        out = masks[0]
        for m in masks[1:]:
            out = out | m
        return _full(inv, size) & ~out

    prop = f.prop
    if prop is None:
        raise ValueError(f"filter {op} requires a path")

    if op == Operator.IS_NULL:
        null_mask = _from_ids(inv.null_ids(prop), size)
        if f.value:
            return null_mask
        return _full(inv, size) & ~null_mask

    if op == Operator.WITHIN_GEO_RANGE:
        grid = inv.geo_grid(prop)
        if not len(grid):
            return np.zeros(size, dtype=bool)
        spec = f.value  # {"geoCoordinates": {latitude, longitude}, "distance": {"max": m}}
        center = spec.get("geoCoordinates", spec)
        max_m = float(spec["distance"]["max"] if "distance" in spec
                      else spec["max"])
        clat = float(center["latitude"])
        clon = float(center["longitude"])
        # grid prune first (sublinear), exact haversine on the survivors
        pos = grid.candidate_positions(clat, clon, max_m)
        d = _geo_distance_m(clat, clon, grid.lats[pos], grid.lons[pos])
        mask = np.zeros(size, dtype=bool)
        cand_ids = grid.ids[pos]
        hit = cand_ids[(d <= max_m) & (cand_ids < size)]
        mask[hit] = True
        return mask

    if op in Operator.RANGE:
        threshold = f.value
        if isinstance(threshold, str):
            threshold = parse_date(threshold)
        threshold = float(threshold)
        # LSM range scan over order-preserving numeric keys; array props
        # index every element, giving any-element semantics for free
        # (reference: searcher.go range row readers)
        if op == Operator.GREATER_THAN:
            ids = inv.numeric_range_ids(prop, threshold, None, lo_incl=False)
        elif op == Operator.GREATER_THAN_EQUAL:
            ids = inv.numeric_range_ids(prop, threshold, None, lo_incl=True)
        elif op == Operator.LESS_THAN:
            ids = inv.numeric_range_ids(prop, None, threshold, hi_incl=False)
        else:
            ids = inv.numeric_range_ids(prop, None, threshold, hi_incl=True)
        return _from_ids(ids, size)

    if op == Operator.LIKE:
        # ?/* wildcards range-scanned over the text vocabulary
        # (reference: inverted/like_regexp.go)
        pattern = str(f.value).lower()
        rx = re.compile(fnmatch.translate(pattern))
        mask = np.zeros(size, dtype=bool)
        for token, ids in inv.text_vocab(prop):
            if rx.match(token.lower()):
                mask |= _from_ids(ids, size)
        return mask

    if op in (Operator.EQUAL, Operator.NOT_EQUAL,
              Operator.CONTAINS_ANY, Operator.CONTAINS_ALL):
        values = f.value if isinstance(f.value, (list, tuple)) else [f.value]
        masks = [_match_value(inv, prop, v, size) for v in values]
        if op == Operator.CONTAINS_ALL:
            out = masks[0]
            for m in masks[1:]:
                out = out & m
            return out
        out = masks[0]
        for m in masks[1:]:
            out = out | m
        if op == Operator.NOT_EQUAL:
            return _full(inv, size) & ~out
        return out

    raise ValueError(f"unknown filter operator {op!r}")


def _match_value(inv: InvertedIndex, prop: str, value, size: int) -> np.ndarray:
    """Exact-match a single value against the filterable index. Text values
    tokenize; multi-token text matches docs containing ALL tokens
    (reference Equal-on-text semantics)."""
    if isinstance(value, bool):
        return _from_ids(inv.filterable_ids(prop, value), size)
    if isinstance(value, (int, float)):
        return _from_ids(inv.filterable_ids(prop, float(value)), size)
    if isinstance(value, str):
        # date-valued? keys are floats for date props
        sch = inv.config.property(prop)
        if sch is not None and sch.data_type in (DataType.DATE, DataType.DATE_ARRAY):
            try:
                return _from_ids(inv.filterable_ids(prop, parse_date(value)), size)
            except ValueError:
                return np.zeros(size, dtype=bool)
        if sch is not None and sch.data_type in (DataType.UUID, DataType.UUID_ARRAY):
            return _from_ids(inv.filterable_ids(prop, value), size)
        tokenization = sch.tokenization if sch is not None else "word"
        tokens = tokenize(value, tokenization)
        if not tokens:
            return np.zeros(size, dtype=bool)
        out = _from_ids(inv.filterable_ids(prop, tokens[0]), size)
        for t in tokens[1:]:
            out = out & _from_ids(inv.filterable_ids(prop, t), size)
        return out
    return np.zeros(size, dtype=bool)
