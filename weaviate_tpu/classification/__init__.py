"""Classification: assign property values from vector neighborhoods.

Reference: usecases/classification — POST /v1/classifications starts an
async job that classifies every object of a class missing the target
property, polled via GET /v1/classifications/{id}. Types:

- ``knn``           majority vote over the k nearest *labeled* objects of
                    the same class (classifier_knn.go); training set can
                    be narrowed with trainingSetWhere.
- ``zeroshot``      assign the nearest object of the target class — no
                    labeled examples needed, similarity between the source
                    object's vector and candidate label objects' vectors
                    (classifier_zeroshot.go).

Batched TPU re-design: instead of the reference's per-object kNN loop,
all unclassified vectors form one [B, d] query block scored against the
labeled/candidate corpus in a single chunked scan (ops.topk), so the
whole classification run is a handful of device calls.
"""

from __future__ import annotations

import threading
import time
import uuid as uuid_mod
from collections import Counter

import numpy as np

RUNNING = "running"
COMPLETED = "completed"
FAILED = "failed"


class ClassificationError(Exception):
    pass


class ClassificationManager:
    def __init__(self, db, modules=None):
        self.db = db
        self.modules = modules
        self._lock = threading.Lock()
        self._jobs: dict[str, dict] = {}

    # -- API -----------------------------------------------------------------

    def start(self, class_name: str, classify_properties: list[str],
              based_on_properties: list[str] | None = None,
              kind: str = "knn", settings: dict | None = None,
              where=None, training_set_where=None,
              tenant: str | None = None,
              wait: bool = False) -> dict:
        """Returns the job descriptor (id + status), reference:
        handlers_classification.go → classification.Classifier.Schedule."""
        settings = settings or {}
        col = self.db.get_collection(class_name)  # KeyError → 404 upstream
        if col.config.multi_tenancy.enabled and not tenant:
            # never mix tenants' objects into one training set
            raise ClassificationError(
                "classification on a multi-tenant class requires a tenant")
        if kind == "text2vec-contextionary-contextual":
            kind = "contextual"  # reference TypeContextual (validation.go:24)
        if kind not in ("knn", "zeroshot", "contextual"):
            raise ClassificationError(f"unknown classification type {kind!r}")
        if not classify_properties:
            raise ClassificationError("classifyProperties must not be empty")
        for p in classify_properties:
            if col.config.property(p) is None:
                raise ClassificationError(
                    f"class {class_name} has no property {p!r}")
        if kind in ("zeroshot", "contextual") and \
                not settings.get("targetClass"):
            raise ClassificationError(
                f"{kind} needs settings.targetClass (the class whose "
                "objects are the candidate labels)")
        if kind == "contextual" and not based_on_properties:
            raise ClassificationError(
                "contextual classification needs basedOnProperties (the "
                "text whose words are TF-IDF ranked)")

        job_id = str(uuid_mod.uuid4())
        try:
            k_setting = int(settings.get("k", 3))
        except (TypeError, ValueError):
            raise ClassificationError(
                f"settings.k must be an integer, got {settings.get('k')!r}")
        job = {
            "id": job_id,
            "class": class_name,
            "classifyProperties": classify_properties,
            "basedOnProperties": based_on_properties or [],
            "type": kind,
            "settings": {**settings, "k": k_setting},
            "status": RUNNING,
            "error": None,
            "meta": {"started": time.time(), "count": 0,
                     "countSucceeded": 0, "countFailed": 0},
        }
        with self._lock:
            self._jobs[job_id] = job

        def work():
            try:
                if kind == "knn":
                    self._run_knn(col, job, where, training_set_where,
                                  tenant)
                elif kind == "contextual":
                    self._run_contextual(col, job, where, tenant)
                else:
                    self._run_zeroshot(col, job, where, tenant)
                job["status"] = COMPLETED
                job["meta"]["completed"] = time.time()
            except Exception as e:
                job["status"] = FAILED
                job["error"] = str(e)

        t = threading.Thread(target=work, daemon=True,
                             name=f"classification-{job_id[:8]}")
        t.start()
        if wait:
            t.join()
        return dict(job)

    def get(self, job_id: str) -> dict:
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None:
            raise KeyError(f"classification {job_id!r} not found")
        return dict(job)

    # -- engines -------------------------------------------------------------

    def _split(self, col, props: list[str], source_where,
               training_where=None, tenant: str | None = None):
        """(unlabeled, labeled) object lists. labeled = every classify
        property present and non-empty. ``source_where`` narrows which
        objects get classified; ``training_where`` narrows the training
        set (reference: filters.sourceWhere / trainingSetWhere,
        usecases/classification/filters.go). Masks are evaluated
        PER SHARD — doc ids are per-shard counters, so one shard's mask
        must never be applied to another shard's objects."""
        from weaviate_tpu.filters.filters import compute_allow_mask
        from weaviate_tpu.storage.objects import StorageObject

        unlabeled, labeled = [], []
        # MT collections classify ONE tenant's shard; others span all local
        # shards (col._target_shards enforces the tenant requirement)
        for shard in col._target_shards(tenant):
            src_mask = train_mask = None
            if source_where is not None:
                src_mask = compute_allow_mask(source_where, shard._inverted,
                                              shard.doc_id_space)
            if training_where is not None:
                train_mask = compute_allow_mask(training_where,
                                                shard._inverted,
                                                shard.doc_id_space)

            def hit(mask, obj):
                return mask is None or (obj.doc_id < len(mask)
                                        and mask[obj.doc_id])

            for _key, raw in shard.objects.iter_items():
                obj = StorageObject.from_bytes(raw)
                if obj.vector is None:
                    continue
                has_all = all(obj.properties.get(p) not in (None, "", [])
                              for p in props)
                if has_all:
                    if hit(train_mask, obj):
                        labeled.append(obj)
                elif hit(src_mask, obj):
                    unlabeled.append(obj)
        return unlabeled, labeled

    @staticmethod
    def _unit(rows: list[np.ndarray]) -> np.ndarray:
        """Stack + L2-normalize: stored object vectors are RAW (the index
        normalizes on add, the object store does not), so cosine ranking
        here must normalize both sides itself."""
        m = np.stack(rows).astype(np.float32)
        norms = np.linalg.norm(m, axis=1, keepdims=True)
        return m / np.where(norms > 1e-30, norms, 1.0)

    def _run_knn(self, col, job, where, training_set_where,
                 tenant=None):
        from weaviate_tpu.ops.topk import chunked_topk
        import jax.numpy as jnp

        props = job["classifyProperties"]
        k = job["settings"]["k"]
        unlabeled, labeled = self._split(col, props, where,
                                         training_set_where, tenant)
        job["meta"]["count"] = len(unlabeled)
        if not unlabeled:
            return
        if not labeled:
            raise ClassificationError(
                "no labeled training objects (every object is missing the "
                "classify properties)")
        q = self._unit([o.vector for o in unlabeled])
        x = self._unit([o.vector for o in labeled])
        k_eff = min(k, len(labeled))
        # one batched scan: [B, d] x [N, d] -> [B, k] neighbor indices
        _, idx = chunked_topk(jnp.asarray(q), jnp.asarray(x), k=k_eff,
                              metric="cosine")
        idx = np.asarray(idx)
        for row, obj in enumerate(unlabeled):
            try:
                updates = {}
                for p in props:
                    votes = Counter()
                    for j in idx[row]:
                        if j < 0:
                            continue
                        v = labeled[int(j)].properties.get(p)
                        key = tuple(sorted(map(str, v))) \
                            if isinstance(v, list) else v
                        votes[key] += 1
                    if votes:
                        winner = votes.most_common(1)[0][0]
                        updates[p] = list(winner) \
                            if isinstance(winner, tuple) else winner
                self._apply(col, obj, updates, tenant)
                job["meta"]["countSucceeded"] += 1
            except Exception:
                job["meta"]["countFailed"] += 1

    def _run_contextual(self, col, job, where, tenant=None):
        """Contextual classification (reference TypeContextual:
        modules/text2vec-contextionary/classification/
        classifier_run_contextual.go + tf_idf.go): no training data.
        The basedOn words of the UNCLASSIFIED corpus are TF-IDF ranked;
        per object only the informative fraction (above
        ``tfidfCutoffPercentile``, default 50) forms a query that the
        class's vectorizer embeds, and the nearest target-class object by
        cosine wins. Falls back to the object's stored vector when no
        vectorizer module is configured."""
        import math

        import jax.numpy as jnp

        from weaviate_tpu.ops.topk import chunked_topk
        from weaviate_tpu.text.tokenizer import tokenize

        props = job["classifyProperties"]
        based_on = job["basedOnProperties"]
        settings = job["settings"]
        cutoff = float(settings.get("tfidfCutoffPercentile", 50))
        target = self.db.get_collection(settings["targetClass"])
        candidates = [o for o in target.iter_objects()
                      if o.vector is not None]
        if not candidates:
            raise ClassificationError(
                f"target class {target.config.name} has no vectorized "
                "objects")
        unlabeled, _ = self._split(col, props, where, tenant=tenant)
        job["meta"]["count"] = len(unlabeled)
        if not unlabeled:
            return
        # corpus-wide document frequencies over the basedOn text
        docs_tokens = []
        df = Counter()
        for obj in unlabeled:
            text = " ".join(str(obj.properties.get(p, ""))
                            for p in based_on)
            toks = tokenize(text, "word")
            docs_tokens.append(toks)
            df.update(set(toks))
        n_docs = len(unlabeled)

        def query_text(toks: list[str]) -> str:
            if not toks:
                return ""
            tf = Counter(toks)
            scored = sorted(
                ((tf[w] / len(toks)) * math.log(1 + n_docs / df[w]), w)
                for w in tf)
            keep = max(1, int(len(scored) * (1 - cutoff / 100.0)))
            top = [w for _s, w in scored[-keep:]]
            # preserve original word order for the vectorizer
            top_set = set(top)
            return " ".join(w for w in toks if w in top_set)

        texts = [query_text(toks) for toks in docs_tokens]
        vecs: list = [None] * len(unlabeled)
        if self.modules is not None and any(texts):
            # vectorizer calls are HTTP round trips — run them
            # concurrently, not one serial call per object
            from concurrent.futures import ThreadPoolExecutor

            def embed(i):
                if not texts[i]:
                    return
                try:
                    vecs[i] = np.asarray(self.modules.vectorize_query(
                        col.config, texts[i], ""), dtype=np.float32)
                except Exception:
                    vecs[i] = None

            with ThreadPoolExecutor(max_workers=8) as pool:
                list(pool.map(embed, range(len(unlabeled))))
        q_rows = []
        for obj, vec in zip(unlabeled, vecs):
            if vec is None:
                vec = obj.vector
            if vec is None:
                raise ClassificationError(
                    f"object {obj.uuid} has no vector and no vectorizer "
                    "module is configured")
            q_rows.append(np.asarray(vec, dtype=np.float32))
        q = self._unit(q_rows)
        x = self._unit([o.vector for o in candidates])
        _, idx = chunked_topk(jnp.asarray(q), jnp.asarray(x), k=1,
                              metric="cosine")
        idx = np.asarray(idx)
        self._assign_targets(col, job, unlabeled, candidates, target, idx,
                             props, tenant)

    def _assign_targets(self, col, job, unlabeled, candidates, target, idx,
                        props, tenant):
        """Write the chosen target per object (shared by zeroshot and
        contextual — beacon for cref props, label text otherwise)."""
        for row, obj in enumerate(unlabeled):
            try:
                best = candidates[int(idx[row, 0])]
                updates = {}
                for p in props:
                    prop_cfg = col.config.property(p)
                    if prop_cfg is not None and prop_cfg.data_type == "cref":
                        updates[p] = [{
                            "beacon": "weaviate://localhost/"
                                      f"{target.config.name}/{best.uuid}"}]
                    else:
                        label = next(
                            (v for v in best.properties.values()
                             if isinstance(v, str)), best.uuid)
                        updates[p] = label
                self._apply(col, obj, updates, tenant)
                job["meta"]["countSucceeded"] += 1
            except Exception:
                job["meta"]["countFailed"] += 1

    def _run_zeroshot(self, col, job, where, tenant=None):
        from weaviate_tpu.ops.topk import chunked_topk
        import jax.numpy as jnp

        props = job["classifyProperties"]
        target = self.db.get_collection(job["settings"]["targetClass"])
        candidates = [o for o in target.iter_objects()
                      if o.vector is not None]
        if not candidates:
            raise ClassificationError(
                f"target class {target.config.name} has no vectorized "
                "objects")
        unlabeled, _ = self._split(col, props, where, tenant=tenant)
        job["meta"]["count"] = len(unlabeled)
        if not unlabeled:
            return
        q = self._unit([o.vector for o in unlabeled])
        x = self._unit([o.vector for o in candidates])
        _, idx = chunked_topk(jnp.asarray(q), jnp.asarray(x), k=1,
                              metric="cosine")
        idx = np.asarray(idx)
        self._assign_targets(col, job, unlabeled, candidates, target, idx,
                             props, tenant)

    @staticmethod
    def _apply(col, obj, updates: dict, tenant=None) -> None:
        if not updates:
            return
        props = dict(obj.properties)
        props.update(updates)
        col.put_object(props, vector=obj.vector,
                       vectors=obj.vectors or None, uuid=obj.uuid,
                       tenant=tenant,
                       creation_time_ms=obj.creation_time_ms)
