"""Device-resident result handles and the double-buffered D2H drain.

The serving gap (ROADMAP item 1, BENCH_r04): the device sustains ~450k
QPS on flat bf16 b=1024 while the served path peaks at ~12k — the
difference lives in the Python stack, and the single worst offender is
the synchronous ``np.asarray`` at the end of every search: the dispatch
thread blocks on the device, the device then idles while Python slices
and routes results, and neither side ever overlaps the other.

This module is the fix's substrate (ISSUE 7 tentpole):

- ``DeviceResultHandle`` — a future for one dispatched device program.
  The engine's ``search_async`` entry points return it instead of numpy;
  the raw device arrays stay resident until ``.result()`` performs THE
  sanctioned device->host transfer (``tracing.d2h`` — recorded as a
  ``transfer.d2h`` span with device-time attribution on sampled traces)
  and runs the host-side ``finish`` post-step (slot -> doc-id
  resolution, gathered-path remapping, exact rescore). Handles compose
  with ``map`` so each layer adds its host post-processing without
  forcing the transfer early.

- ``TransferPipeline`` — a dedicated drain thread with a bounded
  in-flight window (double buffering). The query batcher and the native
  data plane submit (handle, callback) pairs: while batch N's results
  cross D2H here, the dispatch thread is already launching batch N+1's
  program, so the device never idles on a host sync. The window bound
  (default 2) is backpressure: batch N+2's dispatch waits until N has
  fully drained, keeping staged host memory and device in-flight work
  bounded.

This file is deliberately OUTSIDE graftlint G1's hot-path scope: it IS
the API boundary the checker tells hot paths to move their transfers to
(the same standing tracing.py has for its sampled ``device_sync``).
G9's drain rule carries the same exemption (``DRAIN_EXEMPT``): the
drain thread's ONE blocking wait lives here by design, and the
whole-program walk flags any submitted callback that reaches a second
sync — keep callbacks host-only and post-process off-thread.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from weaviate_tpu.runtime import faultline, tracing

_UNSET = object()


def d2h(*values):
    """THE sanctioned device->host fetch for maintenance paths (epoch
    compaction, store rebuilds, migration serialization): delegates to
    ``tracing.d2h`` so the copy lands in a ``transfer.d2h`` span with
    device-time attribution on sampled traces. Serving paths should ride
    ``DeviceResultHandle`` instead — this direct form is for host-side
    rebuild work where a future adds nothing."""
    return tracing.d2h(*values)


class DeviceResultHandle:
    """Future-like handle for one dispatched device program's results.

    ``arrays`` are the raw device (jax) arrays the program returns; they
    stay device-resident until ``result()`` runs. ``finish(*host)`` is
    the host-side post-step applied to the fetched numpy arrays; its
    return value is the handle's result. ``result()`` is idempotent and
    thread-safe (an error is cached and re-raised to every caller).
    """

    __slots__ = ("_arrays", "_finish", "_parent", "_value", "_error",
                 "_lock", "attrs")

    def __init__(self, arrays=(), finish=None, parent=None, attrs=None):
        self._arrays = tuple(arrays)
        self._finish = finish
        self._parent = parent
        self._value = _UNSET
        self._error: BaseException | None = None
        self._lock = threading.Lock()
        self.attrs = dict(attrs or {})

    @property
    def arrays(self) -> tuple:
        """The raw device arrays, still resident (empty once ``result()``
        has drained them, or for ``ready()``/``map()`` handles). Device
        COMPOSITION hook: the hybridplane feeds a dense scan's arrays
        into the fusion program without forcing the D2H early."""
        return self._arrays

    @classmethod
    def ready(cls, value) -> "DeviceResultHandle":
        """A handle over an already-host-resident result (sync fallbacks
        keep the async call signature without a fake transfer)."""
        h = cls()
        h._value = value
        return h

    def map(self, fn) -> "DeviceResultHandle":
        """Chain a host post-step: the new handle resolves to
        ``fn(self.result())``. The transfer still happens exactly once,
        at the outermost ``result()``."""
        return DeviceResultHandle(parent=self, finish=fn,
                                  attrs=dict(self.attrs))

    @property
    def done(self) -> bool:
        return self._value is not _UNSET or self._error is not None

    def result(self):
        """Fetch to host (``transfer.d2h``) and run the finish chain."""
        with self._lock:
            if self._error is not None:
                raise self._error
            if self._value is not _UNSET:
                return self._value
            try:
                if self._parent is not None:
                    host = self._parent.result()
                    self._value = (self._finish(host)
                                   if self._finish is not None else host)
                else:
                    # faultline point: the sanctioned D2H boundary — an
                    # injected error is cached like a real fetch failure
                    # and reaches every waiter of THIS handle only
                    faultline.fire("transfer.d2h", arrays=len(self._arrays))
                    host = tracing.d2h(*self._arrays)
                    self._value = (self._finish(*host)
                                   if self._finish is not None else host)
            except BaseException as e:  # cache: every waiter sees it
                self._error = e
                raise
            finally:
                self._arrays = ()  # release the device references
                self._parent = None
            return self._value


class TransferPipeline:
    """Dedicated D2H drain thread with a bounded in-flight window.

    ``submit(handle, callback, ctx)`` enqueues one transfer; it BLOCKS
    while ``depth`` transfers are already queued or running — that bound
    is the double-buffering contract (depth=2: batch N draining, batch
    N+1 dispatched, batch N+2's dispatcher waits). ``callback(value,
    error, t_fetch_start, t_fetch_end)`` runs on the drain thread;
    ``ctx`` (a ``tracing.capture()`` handle) scopes the fetch so the
    ``transfer.d2h`` span lands in a real request trace.

    ``stop()`` drains everything already submitted — in-flight waiters
    get their results (or the fetch error), never a hang — then joins
    the thread. Submitting after stop raises.
    """

    def __init__(self, depth: int = 2, name: str = "d2h-transfer"):
        self.depth = max(1, int(depth))
        self.name = name
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._inflight = 0
        self._thread: threading.Thread | None = None
        self._stopped = False
        # observability (bench/tests assert overlap through these)
        self.transferred = 0
        self.errors = 0

    @property
    def inflight(self) -> int:
        """Transfers queued or currently draining."""
        with self._cv:
            return len(self._q) + self._inflight

    def wait_slot(self) -> None:
        """Block until the window has a free slot (or the pipeline is
        stopped). Dispatchers call this BEFORE draining their queue so
        requests keep coalescing while the window is full — racing ahead
        with tiny batches would trade the batching win for the overlap
        win instead of keeping both."""
        with self._cv:
            while (not self._stopped
                   and len(self._q) + self._inflight >= self.depth):
                self._cv.wait(timeout=1.0)

    def submit(self, handle: DeviceResultHandle, callback, ctx=None):
        # kernelscope's dispatch-submit stamp: paired with the drain
        # thread's post-``result()`` stamp (t_fetch_end in the callback)
        # it bounds the device+memcpy window of this handle without a
        # single added sync — the drain blocks on the D2H anyway
        handle.attrs.setdefault("t_submit", time.perf_counter())
        with self._cv:
            while (not self._stopped
                   and len(self._q) + self._inflight >= self.depth):
                self._cv.wait(timeout=1.0)
            if self._stopped:
                raise RuntimeError(f"transfer pipeline {self.name} stopped")
            self._q.append((handle, callback, ctx))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name=self.name, daemon=True)
                self._thread.start()
            self._cv.notify_all()

    def stop(self, timeout: float = 10.0) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
            t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout)

    def _run(self):
        while True:
            with self._cv:
                while not self._q and not self._stopped:
                    self._cv.wait(timeout=1.0)
                if not self._q:  # stopped and drained
                    self._cv.notify_all()
                    return
                handle, callback, ctx = self._q.popleft()
                self._inflight += 1
            err = None
            value = None
            t0 = time.perf_counter()
            try:
                value = tracing.run_in(ctx, handle.result)
            except BaseException as e:  # noqa: BLE001 — deliver to waiters
                err = e
            t1 = time.perf_counter()
            try:
                callback(value, err, t0, t1)
            except Exception:  # noqa: BLE001 — a bad callback must not
                pass           # kill the drain thread for later batches
            with self._cv:
                self._inflight -= 1
                self.transferred += 1
                if err is not None:
                    self.errors += 1
                self._cv.notify_all()
