"""Server-side dynamic query batching.

Round-1 gap (VERDICT item 6): each concurrent Search dispatched its own
device program, so N clients paid N host->device round trips while the
scan kernel itself amortizes perfectly over a query batch
(`FlatIndex.search_by_vector_batch` runs one matmul for B queries).

Design (continuous batching, not a fixed window): a request that finds
the device idle dispatches IMMEDIATELY — zero added latency for a lone
client. Requests that arrive while a dispatch is in flight queue up; the
worker drains the whole queue into ONE batched dispatch as soon as the
device frees up. Under load the batch size self-tunes to the arrival
rate, exactly like continuous batching in model serving.

Only unfiltered requests coalesce: the scan kernel applies one validity
mask per dispatch, so a request with an AllowList mask dispatches alone
(the reference's filtered searches take a different path too —
flat_search_cutoff). Mixed k's batch together at max(k) and slice.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from weaviate_tpu.runtime import tracing


class _Pending:
    __slots__ = ("query", "k", "allow", "event", "ids", "dists", "error",
                 "ctx", "t_exec_start", "t_exec_end", "batch_size")

    def __init__(self, query, k, allow):
        self.query = query
        self.k = k
        self.allow = allow
        self.event = threading.Event()
        self.ids = None
        self.dists = None
        self.error: Exception | None = None
        # trace context of the submitting request: the worker dispatches
        # under ONE waiter's context (device spans land in that trace)
        # and stamps exec timings every waiter records into its own
        self.ctx = tracing.capture()
        self.t_exec_start: float | None = None
        self.t_exec_end: float | None = None
        self.batch_size = 1


class QueryBatcher:
    """Wraps one vector index's batched search entry point.

    ``batch_fn(queries [B,d], k, allow) -> (ids [B,k], dists [B,k])``.
    """

    def __init__(self, batch_fn, max_batch: int = 256):
        self._batch_fn = batch_fn
        self.max_batch = max_batch
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: list[_Pending] = []
        self._worker: threading.Thread | None = None
        self._stopped = False
        # observability (tools/bench_e2e asserts coalescing happens)
        self.dispatches = 0
        self.batched_queries = 0

    def _ensure_worker(self):
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, name="query-batcher", daemon=True)
            self._worker.start()

    def stop(self):
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    def search(self, query: np.ndarray, k: int,
               allow: np.ndarray | None = None):
        """Blocking per-request entry; coalesces under concurrency."""
        item = _Pending(np.asarray(query, dtype=np.float32), k, allow)
        t_enqueue = time.perf_counter()
        with self._cv:
            self._queue.append(item)
            self._ensure_worker()
            self._cv.notify()
        item.event.wait()
        # wait-vs-execute split, recorded into THIS request's trace from
        # the worker's stamps (the worker thread has no request context)
        if item.t_exec_start is not None:
            tracing.record_span("batcher.wait", t_enqueue,
                                item.t_exec_start)
            tracing.record_span("batcher.execute", item.t_exec_start,
                                item.t_exec_end or time.perf_counter(),
                                batch=item.batch_size)
            from weaviate_tpu.runtime.metrics import (
                batcher_execute_duration, batcher_wait_duration)

            batcher_wait_duration.observe(item.t_exec_start - t_enqueue)
            if item.t_exec_end is not None:
                batcher_execute_duration.observe(
                    item.t_exec_end - item.t_exec_start)
        if item.error is not None:
            raise item.error
        return item.ids, item.dists

    # -- worker ---------------------------------------------------------------

    def _run(self):
        while True:
            with self._cv:
                while not self._queue and not self._stopped:
                    self._cv.wait(timeout=1.0)
                if self._stopped:
                    for it in self._queue:
                        it.error = RuntimeError("query batcher stopped")
                        it.event.set()
                    self._queue.clear()
                    return
                drained = self._queue[: self.max_batch]
                del self._queue[: len(drained)]
            try:
                from weaviate_tpu.runtime.metrics import batcher_batch_size

                batcher_batch_size.observe(len(drained))
                self._dispatch(drained)
            except Exception as e:  # noqa: BLE001 — deliver to every waiter
                for it in drained:
                    if not it.event.is_set():
                        it.error = e
                        it.event.set()

    def _dispatch(self, drained: list[_Pending]):
        # filtered requests run alone (one mask per device dispatch);
        # unfiltered requests coalesce into one batched program
        plain = [it for it in drained if it.allow is None]
        masked = [it for it in drained if it.allow is not None]
        for it in masked:
            try:
                it.t_exec_start = time.perf_counter()
                ids, dists = tracing.run_in(
                    it.ctx, self._batch_fn, it.query[None, :], it.k,
                    it.allow)
                it.ids, it.dists = ids[0], dists[0]
            except Exception as e:  # noqa: BLE001
                it.error = e
            it.t_exec_end = time.perf_counter()
            it.event.set()
        if not plain:
            return
        k_max = max(it.k for it in plain)
        queries = np.stack([it.query for it in plain])
        self.dispatches += 1
        self.batched_queries += len(plain)
        # the shared dispatch runs under ONE waiter's trace context (the
        # first traced one) so device-level spans attribute somewhere
        # real; every waiter still records its own wait/execute split
        # from the stamps below
        ctx = next((it.ctx for it in plain if it.ctx is not None), None)
        t0 = time.perf_counter()
        for it in plain:
            it.t_exec_start = t0
            it.batch_size = len(plain)
        try:
            ids, dists = tracing.run_in(ctx, self._batch_fn, queries,
                                        k_max, None)
        except Exception as e:  # noqa: BLE001
            t1 = time.perf_counter()
            for it in plain:
                it.t_exec_end = t1
                it.error = e
                it.event.set()
            return
        t1 = time.perf_counter()
        for row, it in enumerate(plain):
            it.t_exec_end = t1
            it.ids = ids[row, : it.k]
            it.dists = dists[row, : it.k]
            it.event.set()
