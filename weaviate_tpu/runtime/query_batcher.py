"""Server-side dynamic query batching.

Round-1 gap (VERDICT item 6): each concurrent Search dispatched its own
device program, so N clients paid N host->device round trips while the
scan kernel itself amortizes perfectly over a query batch
(`FlatIndex.search_by_vector_batch` runs one matmul for B queries).

Design (continuous batching, not a fixed window): a request that finds
the device idle dispatches IMMEDIATELY — zero added latency for a lone
client. Requests that arrive while a dispatch is in flight queue up; the
worker drains the whole queue into ONE batched dispatch as soon as the
device frees up. Under load the batch size self-tunes to the arrival
rate, exactly like continuous batching in model serving.

Filtered requests coalesce too (ISSUE 3): when the index advertises
``supports_batched_filters`` the drain ships each request's allow list
alongside its query row and the engine folds them into per-query packed
bitmasks consumed INSIDE the scan kernels — one device program serves a
mixed filtered/unfiltered drain (unfiltered rows ride an all-ones mask;
a drain with no filters skips mask handling entirely). Two escape
hatches stay on the solo path: index types without batched-filter
support, and HIGHLY SELECTIVE filters, which the per-dispatch heuristic
routes to the store's gathered cutover (engine/store.py: scanning a
dense gather of the few allowed rows beats a full masked scan below
~capacity/8; the batcher uses a stricter /64 cut because a solo dispatch
also forfeits batching).

Drained batches are padded to power-of-two B buckets and k is bucketed
the same way, so the number of compiled program variants is bounded by
log2(max_batch) * log2(max k) instead of one executable per observed
(batch, k) combination. Mixed k's batch together at the k bucket and
slice.

Zero-sync pipeline (ISSUE 7): with an ``async_batch_fn`` (an index
``search_by_vector_batch_async`` returning a device-resident
``DeviceResultHandle``), the worker becomes a pure DISPATCH loop — it
launches batch N's program and hands the handle to a dedicated transfer
thread (runtime/transfer.py, double-buffered), then immediately drains
and dispatches batch N+1 while N's results cross D2H. The device never
idles on a host sync, and the host-side result routing (row slicing,
waiter wakeup) for batch N overlaps batch N+1's device time. The
transfer window (depth 2) is backpressure: at most two batches are in
flight past dispatch, so staged host memory stays bounded. Results are
bit-identical to the sync path — same program, same padding, same
slicing; only WHERE the transfer happens moves.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from weaviate_tpu.runtime import (degrade, faultline, kernelscope, retry,
                                  tailboard, tracing)
from weaviate_tpu.runtime.transfer import TransferPipeline

#: bounded intake: past this queue depth the batcher sheds load with a
#: typed retriable OverloadedError (REST surfaces it as 503 +
#: Retry-After) instead of accepting latency it can never serve
DEFAULT_MAX_QUEUE = int(os.environ.get("WEAVIATE_TPU_BATCHER_MAX_QUEUE",
                                       "4096"))


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class DeviceHybridUnavailable(RuntimeError):
    """The drain carried hybrid (sparse+dense) requests but the index
    could not run the fused device program for this dispatch shape —
    the shard layer catches this and serves the query through the host
    hybrid path instead."""


class _Pending:
    __slots__ = ("query", "k", "allow", "sparse", "event", "ids", "dists",
                 "error", "ctx", "t_enqueue", "t_exec_start", "t_exec_end",
                 "batch_size", "t_mask_start", "t_mask_end",
                 "t_fetch_start", "t_fetch_end", "epochs",
                 "device_s", "transfer_s", "device_source",
                 "explain_on", "explain")

    def __init__(self, query, k, allow, sparse=None):
        self.query = query
        self.k = k
        self.allow = allow
        # hybrid requests carry their packed sparse operand
        # (ops/bm25.SparseOperand) the way filtered ones carry ``allow``
        self.sparse = sparse
        self.event = threading.Event()
        # enqueue stamp: the flight recorder's wait_ms and the tailboard
        # queue_wait phase both derive from it
        self.t_enqueue = 0.0
        self.ids = None
        self.dists = None
        self.error: Exception | None = None
        # trace context of the submitting request: the worker dispatches
        # under ONE waiter's context (device spans land in that trace)
        # and stamps exec timings every waiter records into its own
        self.ctx = tracing.capture()
        self.t_exec_start: float | None = None
        self.t_exec_end: float | None = None
        self.t_mask_start: float | None = None
        self.t_mask_end: float | None = None
        self.t_fetch_start: float | None = None
        self.t_fetch_end: float | None = None
        self.batch_size = 1
        # epoch fanout of the dispatch this request rode in (the epoch
        # store's handle reports how many per-epoch scans fused into
        # the one merged program) — 0 for single-buffer stores
        self.epochs = 0
        # kernelscope attribution of the dispatch this request rode in:
        # device residency vs memcpy split (source "drain") or the
        # dispatch wall window (source "wall" — sync/null-device paths)
        self.device_s: float | None = None
        self.transfer_s = 0.0
        self.device_source: str | None = None
        # per-query EXPLAIN: captured on the request thread at enqueue
        # (the worker has no request context); the dispatch plan is
        # merged back into the request sink after the waiter wakes
        self.explain_on = kernelscope.explain_enabled()
        self.explain: dict | None = None


class QueryBatcher:
    """Wraps one vector index's batched search entry point.

    ``batch_fn(queries [B,d], k, allow) -> (ids [B,k], dists [B,k])``
    where ``allow`` is None, one shared allow list, or — only when
    ``supports_filter_batching`` — a list of per-request allow lists
    (None entries = unfiltered). ``supports_filter_batching`` may be a
    bool or a zero-arg callable re-read at every dispatch: index
    capabilities change at runtime (DynamicIndex's flat->IVF upgrade,
    ``compress()`` swapping the backing store), and a stale snapshot
    would keep routing filtered requests solo after the index learned
    to coalesce them. ``capacity_fn`` (optional, returns the
    backing store's row capacity) powers the per-dispatch selectivity
    heuristic that routes tiny filters to the solo/gathered path — wire
    it ONLY when the store has a gathered cutover; otherwise solo is a
    full masked scan and strictly worse than batching. ``pad_pow2``
    pads drains to pow2 B/k buckets — right for jitted device programs
    (bounds compiled variants), wasted work for per-row host indexes
    like HNSW (padded rows run real graph searches), so those opt out.
    """

    def __init__(self, batch_fn, max_batch: int = 256,
                 supports_filter_batching: bool = False,
                 capacity_fn=None, pad_pow2: bool = True,
                 owner: dict | None = None, async_batch_fn=None,
                 transfer_depth: int = 2,
                 max_queue: int | None = None, kind: str = "index",
                 hybrid_batch_fn=None):
        from weaviate_tpu.runtime import hbm_ledger

        self._batch_fn = batch_fn
        # hybrid dataplane: ``hybrid_batch_fn(queries, k, allows,
        # sparses) -> DeviceResultHandle | None`` runs the fused
        # sparse+dense program for drains carrying sparse operands
        # (None = unavailable for this dispatch shape -> the hybrid
        # waiters get a typed DeviceHybridUnavailable and the host path
        # takes over at the shard layer)
        self._hybrid_fn = hybrid_batch_fn
        # index kind label for kernelscope's per-compiled-variant
        # residency EWMA (the shard passes the index's ``index_type``)
        self.kind = str(kind)
        # zero-sync pipeline: ``async_batch_fn(queries, k, allow) ->
        # DeviceResultHandle | None`` (None = this dispatch can't run
        # async, fall back to batch_fn). When set, coalesced drains
        # dispatch-and-go: D2H runs on the transfer thread while the
        # worker drains the next batch.
        self._async_fn = async_batch_fn
        self._transfer: TransferPipeline | None = None
        self._transfer_depth = transfer_depth
        self.max_batch = max_batch
        self.max_queue = DEFAULT_MAX_QUEUE if max_queue is None \
            else max_queue
        self.filter_batching = supports_filter_batching  # bool | callable
        self._capacity_fn = capacity_fn
        self.pad_pow2 = pad_pow2
        # HBM-ledger labels for the padded dispatch buffer (the shard
        # layer passes its collection/shard; standalone batchers fall
        # back to the ambient owner scope)
        self._hbm_owner = owner or hbm_ledger.current_owner()
        # metering labels: one batcher serves one (shard, vector), so
        # every request a dispatch coalesces shares these
        self._meter_labels = (
            str(self._hbm_owner.get("collection") or "-"),
            str(self._hbm_owner.get("tenant") or "-"))
        # health key scoped to THIS batcher's owner: batchers are
        # per-shard/per-vector, and a healthy shard's batch must not
        # clear the unhealthy flag a persistently-broken shard set
        scope = "/".join(str(v) for v in (
            self._hbm_owner.get("collection"), self._hbm_owner.get("shard"))
            if v and v not in ("-", "_unowned"))
        self._component = f"query_batcher:{scope}" if scope \
            else "query_batcher"
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: list[_Pending] = []
        self._worker: threading.Thread | None = None
        self._stopped = False
        self._queue_depth_at_drain = 0
        # observability (tools/bench_e2e asserts coalescing happens;
        # tests/test_query_batcher.py asserts the pipeline overlaps)
        self.dispatches = 0
        self.batched_queries = 0
        self.filtered_batched = 0
        self.hybrid_batched = 0
        self.async_dispatches = 0
        # dispatches launched while a previous batch was still in the
        # transfer window — the overlap the double-buffering exists for
        self.overlapped_dispatches = 0

    def _ensure_worker(self):
        """Caller holds ``_cv`` (search() enqueues under it)."""
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, name="query-batcher", daemon=True)
            self._worker.start()

    def stop(self):
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
            tp = self._transfer
        if tp is not None:
            # drains in-flight handles: every waiter gets its result (or
            # the fetch error), never a hang on shutdown
            tp.stop()

    def _ensure_transfer(self) -> TransferPipeline:
        with self._cv:
            if self._stopped:
                # stop() only stops the pipeline it can SEE — creating
                # one after it looked would leak a never-stopped drain
                # thread and let post-stop dispatches succeed. Raising
                # here routes the in-flight drain to its waiters as an
                # error (via _run's handler / the submit RuntimeError
                # path below).
                raise RuntimeError("query batcher stopped")
            if self._transfer is None:
                self._transfer = TransferPipeline(
                    depth=self._transfer_depth, name="qb-transfer")
            return self._transfer

    def search(self, query: np.ndarray, k: int,
               allow: np.ndarray | None = None, sparse=None):
        """Blocking per-request entry; coalesces under concurrency.

        ``sparse`` (a packed ``ops/bm25.SparseOperand``) marks a hybrid
        request: it rides the coalesced dispatch the way allow lists do
        and the drain runs the fused sparse+dense device program.

        Deadline-aware: a request that arrives with its budget spent
        fails typed BEFORE enqueueing, and the wait below is capped at
        the remaining budget — a client can never hang past its
        deadline on a wedged dispatch. Overload-aware: a full queue
        sheds with a retriable OverloadedError instead of queueing
        latency the budget can't absorb."""
        retry.check("batcher")
        item = _Pending(np.asarray(query, dtype=np.float32), k, allow,
                        sparse)
        t_enqueue = item.t_enqueue = time.perf_counter()
        with self._cv:
            if len(self._queue) >= self.max_queue:
                raise retry.OverloadedError(
                    f"query batcher queue full "
                    f"({len(self._queue)}/{self.max_queue})",
                    retry_after_s=0.1)
            self._queue.append(item)
            self._ensure_worker()
            self._cv.notify()
        rem = retry.remaining()
        if rem is None:
            item.event.wait()
        elif not item.event.wait(timeout=min(rem, threading.TIMEOUT_MAX)):
            # budget spent while queued/dispatched: the worker will
            # still complete the batch (results discarded), but THIS
            # client gets the typed timeout now
            from weaviate_tpu.runtime.metrics import deadline_exceeded_total

            deadline_exceeded_total.labels("batcher").inc()
            raise retry.DeadlineExceeded("batcher")
        # wait-vs-execute split, recorded into THIS request's trace from
        # the worker's stamps (the worker thread has no request context)
        if item.t_exec_start is not None:
            tracing.record_span("batcher.wait", t_enqueue,
                                item.t_exec_start)
            if item.t_mask_start is not None:
                tracing.record_span("batcher.mask_pack", item.t_mask_start,
                                    item.t_mask_end or item.t_mask_start)
            tracing.record_span("batcher.execute", item.t_exec_start,
                                item.t_exec_end or time.perf_counter(),
                                batch=item.batch_size,
                                **({"epochs": item.epochs}
                                   if item.epochs else {}))
            if item.t_fetch_start is not None:
                # the pipelined D2H drain for this request's batch (the
                # transfer thread's handle.result() window)
                tracing.record_span("batcher.transfer",
                                    item.t_fetch_start,
                                    item.t_fetch_end
                                    or item.t_fetch_start)
            from weaviate_tpu.runtime.metrics import (
                batcher_execute_duration, batcher_wait_duration)

            batcher_wait_duration.observe(item.t_exec_start - t_enqueue)
            if item.t_exec_end is not None:
                batcher_execute_duration.observe(
                    item.t_exec_end - item.t_exec_start)
            if item.t_fetch_start is not None \
                    and item.t_fetch_end is not None:
                from weaviate_tpu.runtime.metrics import (
                    batcher_transfer_duration)

                batcher_transfer_duration.observe(
                    item.t_fetch_end - item.t_fetch_start)
            # always-on phase attribution (tailboard), folded into this
            # request's live timeline on the request thread. "device" is
            # kernelscope's attributed residency: the drain-thread stamp
            # window minus the sampled-memcpy EWMA (source=drain,
            # block_until_ready-free) or the dispatch wall window on
            # sync/null-device paths (source=wall); "transfer" is the
            # memcpy share. The pre-kernelscope wall split stays as the
            # fallback for dispatches that died before attribution.
            tailboard.phase("queue_wait", item.t_exec_start - t_enqueue)
            if item.device_s is not None:
                tailboard.phase("device", item.device_s)
                if item.transfer_s > 0:
                    tailboard.phase("transfer", item.transfer_s)
            elif item.t_fetch_start is not None:
                tailboard.phase("device",
                                item.t_fetch_start - item.t_exec_start)
                tailboard.phase("transfer",
                                (item.t_fetch_end or item.t_fetch_start)
                                - item.t_fetch_start)
            elif item.t_exec_end is not None:
                tailboard.phase("device",
                                item.t_exec_end - item.t_exec_start)
        if item.explain is not None:
            # fold the dispatch's plan into the request-level explain
            # sink (installed by the REST/gRPC edge on THIS thread)
            kernelscope.merge_into_request(item.explain)
        if item.error is not None:
            raise item.error
        return item.ids, item.dists

    # -- worker ---------------------------------------------------------------

    def _run(self):
        while True:
            # pipeline pacing: with the transfer window full (one batch
            # computing, one draining), DON'T drain yet — arriving
            # requests keep coalescing into the next batch, so the
            # pipeline keeps the sync path's batch sizes AND the overlap
            tp = self._transfer
            if tp is not None:
                tp.wait_slot()
            with self._cv:
                while not self._queue and not self._stopped:
                    self._cv.wait(timeout=1.0)
                if self._stopped:
                    for it in self._queue:
                        it.error = RuntimeError("query batcher stopped")
                        it.event.set()
                    self._queue.clear()
                    return
                drained = self._queue[: self.max_batch]
                del self._queue[: len(drained)]
                # queue depth AFTER the drain (what the next batch
                # inherits) — the flight recorder's congestion signal
                self._queue_depth_at_drain = len(self._queue)
            try:
                from weaviate_tpu.runtime.metrics import batcher_batch_size

                batcher_batch_size.observe(len(drained))
                self._dispatch(drained)
            except Exception as e:  # noqa: BLE001 — deliver to every waiter
                for it in drained:
                    if not it.event.is_set():
                        it.error = e
                        it.event.set()

    def _allowed_count(self, allow) -> int:
        """Selectivity of an allow list (bool mask over doc-id space or
        array of allowed ids)."""
        a = np.asarray(allow)
        return int(np.count_nonzero(a)) if a.dtype == np.bool_ else a.size

    def _prefer_solo(self, it: _Pending) -> bool:
        """Per-dispatch selectivity heuristic: a HIGHLY selective filter
        beats the batched masked scan by taking the store's gathered
        cutover, which only exists on the solo (shared-mask) path. The
        /64 cut is stricter than the store's /8 crossover because going
        solo also gives up dispatch coalescing."""
        if self._capacity_fn is None:
            return False
        try:
            cap = int(self._capacity_fn())
        except Exception:  # noqa: BLE001 — heuristic only, never fail a query
            return False
        if cap <= 0:
            return False
        return self._allowed_count(it.allow) <= cap // 64

    def _dispatch(self, drained: list[_Pending]):
        # split the drain: filtered requests coalesce with the plain ones
        # into ONE bitmask-batched device program; only index types
        # without batched-filter support and highly selective filters
        # (gathered cutover) dispatch solo
        solo, coal = [], []
        fb = self.filter_batching
        filter_batching = bool(fb() if callable(fb) else fb)
        for it in drained:
            # hybrid requests never go solo: their sparse operand only
            # dispatches through the fused batched program
            if it.sparse is None and it.allow is not None and (
                    not filter_batching or self._prefer_solo(it)):
                solo.append(it)
            else:
                coal.append(it)
        for it in solo:
            plan = {} if it.explain_on else None
            try:
                it.t_exec_start = time.perf_counter()
                if plan is None:
                    ids, dists = tracing.run_in(
                        it.ctx, self._batch_fn, it.query[None, :], it.k,
                        it.allow)
                else:
                    with kernelscope.explain_scope(plan):
                        ids, dists = tracing.run_in(
                            it.ctx, self._batch_fn, it.query[None, :],
                            it.k, it.allow)
                it.ids, it.dists = ids[0], dists[0]
            except Exception as e:  # noqa: BLE001
                it.error = e
            it.t_exec_end = time.perf_counter()
            # no drain stamps on the solo path (sync device call):
            # wall-window attribution, metered against this batcher's
            # owner like any other dispatch
            wall = max(0.0, it.t_exec_end - it.t_exec_start)
            it.device_s, it.transfer_s, it.device_source = wall, 0.0, "wall"
            kernelscope.record_dispatch(self.kind, 1, it.k, wall, "wall")
            kernelscope.meter(*self._meter_labels, wall)
            if plan is not None:
                plan["batcher"] = {
                    "batch": 1, "b_pad": 1, "k_bucket": it.k,
                    "queue_depth": self._queue_depth_at_drain,
                    "filtered": int(it.allow is not None), "solo": True,
                    "async": False, "kind": self.kind}
                it.explain = plan
            it.event.set()
        if not coal:
            # a purely-solo drain still leaves a flight-recorder record
            # (batch=0): the solo/gathered path is exactly the regression
            # surface an r05-style post-hoc investigation digs through
            if solo:
                tailboard.record_dispatch(
                    "batcher", batch=0, b_pad=0, k=0,
                    queue_depth=self._queue_depth_at_drain,
                    wait_ms=round(max(
                        ((it.t_exec_start or it.t_enqueue) - it.t_enqueue)
                        * 1000.0 for it in solo), 3),
                    filtered=len(solo), solo=len(solo),
                    window_inflight=0, epochs=0)
            return
        b = len(coal)
        # pow2 B/k buckets bound the number of compiled variants (one
        # executable per bucket, not per observed batch size); padded
        # query rows are zero vectors whose results are discarded
        if self.pad_pow2:
            b_pad = min(_next_pow2(b), max(self.max_batch, b))
            k_bucket = _next_pow2(max(it.k for it in coal))
        else:
            b_pad = b
            k_bucket = max(it.k for it in coal)
        filtered = [it for it in coal if it.allow is not None]
        hybrid = [it for it in coal if it.sparse is not None]
        t_mask0 = time.perf_counter()
        allows = None
        if filtered:
            # per-request allow lists ride along row-aligned; unfiltered
            # and padded rows are None (all-ones downstream)
            allows = [it.allow for it in coal] + [None] * (b_pad - b)
        sparses = None
        if hybrid:
            # sparse operands ride row-aligned exactly like allow lists;
            # pure-vector and padded rows are None (dense-only downstream)
            sparses = [it.sparse for it in coal] + [None] * (b_pad - b)
        queries = np.zeros((b_pad,) + coal[0].query.shape, dtype=np.float32)
        for row, it in enumerate(coal):
            queries[row] = it.query
        t_mask1 = time.perf_counter()
        self.dispatches += 1
        self.batched_queries += b
        self.filtered_batched += len(filtered)
        from weaviate_tpu.runtime.metrics import (
            batcher_compile_bucket, batcher_filtered_batched)

        batcher_compile_bucket.labels(b=str(b_pad), k=str(k_bucket)).inc()
        if filtered:
            batcher_filtered_batched.inc(len(filtered))
        # the shared dispatch runs under ONE waiter's trace context (the
        # first traced one) so device-level spans attribute somewhere
        # real; every waiter still records its own wait/execute split
        # from the stamps below
        ctx = next((it.ctx for it in coal if it.ctx is not None), None)
        # per-query EXPLAIN: if any coalesced waiter asked, the engine's
        # host-side plan notes emitted during THIS dispatch (the program
        # build on the worker thread) land in one shared sink; explain
        # never changes WHAT is dispatched — sync and async answers stay
        # bit-identical
        plan = {} if any(it.explain_on for it in coal) else None
        t0 = time.perf_counter()
        for it in coal:
            it.t_exec_start = t0
            it.batch_size = b
            if filtered:
                it.t_mask_start, it.t_mask_end = t_mask0, t_mask1
        # flight-recorder dispatch record (lock-free ring): the dispatch
        # history a post-hoc regression investigation replays. epochs is
        # patched in below once the async handle reports its fanout.
        tp0 = self._transfer
        flight_rec = tailboard.record_dispatch(
            "batcher", batch=b, b_pad=b_pad, k=k_bucket,
            queue_depth=self._queue_depth_at_drain,
            wait_ms=round(max(
                (t0 - it.t_enqueue) * 1000.0 for it in coal), 3),
            filtered=len(filtered), solo=len(solo),
            window_inflight=tp0.inflight if tp0 is not None else 0,
            epochs=0)

        def _attribute(device_s: float, transfer_s: float, source: str):
            """Kernelscope fold for this dispatch: stamp every waiter's
            attribution (each reads it back on its own request thread),
            feed the per-compiled-variant residency EWMA + histogram,
            patch the flight record, and meter the apportioned
            residency per tenant."""
            device_s = max(0.0, device_s)
            for it in coal:
                it.device_s = device_s
                it.transfer_s = max(0.0, transfer_s)
                it.device_source = source
            flight_rec["device_ms"] = round(device_s * 1000.0, 3)
            flight_rec["t_source"] = source
            kernelscope.record_dispatch(self.kind, b_pad, k_bucket,
                                        device_s, source)
            # apportion across the coalesced requests, weighted by rows
            # scanned — one batcher serves one (shard, vector), so rows
            # and owner labels are uniform per dispatch: the weights
            # degenerate to an even split and the tenant meter sees the
            # full dispatch residency exactly once
            for share in kernelscope.apportion(device_s,
                                               [1.0] * len(coal)):
                kernelscope.meter(*self._meter_labels, share)

        # the pow2-padded query block becomes a device upload inside
        # batch_fn — ledger-registered until the results leave the
        # device (sync: end of this call; async: transfer completion) so
        # peak watermarks see concurrent drains
        from weaviate_tpu.runtime.hbm_ledger import ledger as _hbm

        pad_key = _hbm.register("dispatch_pad", queries.nbytes,
                                dtype="float32", **self._hbm_owner)

        def _fail(err: BaseException) -> None:
            """Single exit path for every failure mode: release the pad
            exactly once and set EVERY not-yet-delivered waiter's event
            — an unset event hangs its client forever (the transfer
            thread swallows callback exceptions by design)."""
            _hbm.release(pad_key)
            t1 = time.perf_counter()
            for it in coal:
                if not it.event.is_set():
                    it.t_exec_end = t1
                    it.error = err
                    it.event.set()

        def _sync_batch():
            # faultline point: one coalesced device dispatch (the
            # deterministic schedule sees retries as separate calls)
            faultline.fire("batcher.dispatch", batch=b, k=k_bucket)
            if plan is None:
                return tracing.run_in(ctx, self._batch_fn, queries,
                                      k_bucket, allows)
            with kernelscope.explain_scope(plan):
                return tracing.run_in(ctx, self._batch_fn, queries,
                                      k_bucket, allows)

        def _retry_once(first_err: BaseException):
            """Faulted device batch: ONE sync retry. A second failure
            errors only THIS batch's waiters — with the ORIGINAL error,
            the root cause — and flips the batcher's unhealthy flag
            (visible in /v1/nodes); later batches keep serving and
            clear it on success. Returns the (ids, dists) tuple or None
            after failing the waiters."""
            from weaviate_tpu.runtime.metrics import batcher_dispatch_retries

            batcher_dispatch_retries.inc()
            try:
                res2 = _sync_batch()
                # a sync fn that can't actually serve (null-device
                # stubs return None) is a failed retry, not a result
                if not (isinstance(res2, tuple) and len(res2) == 2):
                    raise TypeError(
                        f"batch_fn returned {type(res2).__name__}, "
                        "expected (ids, dists)")
                return res2
            except Exception as e2:  # noqa: BLE001
                degrade.mark_unhealthy(
                    self._component,
                    f"dispatch failed twice: {first_err}; retry: {e2}")
                _fail(first_err)
                return None

        def _mark_served():
            if degrade.is_unhealthy(self._component):
                degrade.mark_healthy(self._component)

        handle = None
        ids = dists = None
        try:
            if hybrid:
                # fused sparse+dense program: there is NO sync fallback
                # for hybrid drains (batch_fn has no sparse-operand
                # slot) — unavailability is a typed error the shard
                # layer converts into the host hybrid path, and the
                # pure-vector remainder re-dispatches normally
                hf = self._hybrid_fn
                if hf is not None:
                    faultline.fire("batcher.dispatch", batch=b,
                                   k=k_bucket)
                    if plan is None:
                        handle = tracing.run_in(ctx, hf, queries,
                                                k_bucket, allows, sparses)
                    else:
                        with kernelscope.explain_scope(plan):
                            handle = tracing.run_in(ctx, hf, queries,
                                                    k_bucket, allows,
                                                    sparses)
                if handle is None:
                    _hbm.release(pad_key)
                    err = DeviceHybridUnavailable(
                        "index cannot run the fused hybrid program for "
                        "this dispatch")
                    t1 = time.perf_counter()
                    for it in hybrid:
                        it.t_exec_end = t1
                        it.error = err
                        it.event.set()
                    rest = [it for it in coal if it.sparse is None]
                    if rest:
                        self._dispatch(rest)
                    return
                self.hybrid_batched += len(hybrid)
                from weaviate_tpu.runtime.metrics import \
                    batcher_hybrid_batched

                batcher_hybrid_batched.inc(len(hybrid))
            elif self._async_fn is not None:
                # dispatch-and-go: launch the program, hand the
                # device-resident handle to the transfer thread, return
                # to drain the NEXT batch while this one crosses D2H
                faultline.fire("batcher.dispatch", batch=b, k=k_bucket)
                if plan is None:
                    handle = tracing.run_in(ctx, self._async_fn, queries,
                                            k_bucket, allows)
                else:
                    # engine plan notes are emitted while the program is
                    # built/launched here (host side); the handle's
                    # finish step runs later on the transfer thread and
                    # stays outside the sink by design
                    with kernelscope.explain_scope(plan):
                        handle = tracing.run_in(ctx, self._async_fn,
                                                queries, k_bucket, allows)
            if handle is not None:
                n_ep = int(handle.attrs.get("epochs", 0) or 0)
                if n_ep:
                    flight_rec["epochs"] = n_ep
                    for it in coal:
                        it.epochs = n_ep
            if handle is None:
                ids, dists = _sync_batch()
        except Exception as e:  # noqa: BLE001
            if hybrid:
                # no sparse-aware sync retry exists — surface the fault
                _fail(e)
                return
            result = _retry_once(e)
            if result is None:
                return
            ids, dists = result
            handle = None
        if plan is not None:
            plan["batcher"] = {
                "batch": b, "b_pad": b_pad, "k_bucket": k_bucket,
                "queue_depth": self._queue_depth_at_drain,
                "filtered": len(filtered), "hybrid": len(hybrid),
                "solo": False,
                "async": handle is not None, "kind": self.kind}
            for it in coal:
                if it.explain_on:
                    it.explain = plan
        if handle is None:
            _hbm.release(pad_key)
            t1 = time.perf_counter()
            # sync path: no drain stamps exist — wall-window attribution
            # with an explicit source label (the null-device deflake
            # guard: degrade, don't crash or report zeros)
            _attribute(t1 - t0, 0.0, "wall")
            self._deliver(coal, ids, dists, t1)
            _mark_served()
            return
        self.async_dispatches += 1
        from weaviate_tpu.runtime.metrics import (batcher_async_dispatched,
                                                  batcher_overlapped)

        batcher_async_dispatched.inc()

        def _finish(res):
            try:
                t1 = time.perf_counter()
                self._deliver(coal, res[0], res[1], t1)
                _hbm.release(pad_key)
                _mark_served()
            except Exception as e:  # noqa: BLE001 — an out-of-contract
                # result shape must surface to the waiters (the sync
                # path raises it through _run's handler)
                _fail(e)

        def _complete(res, err, t_fetch0, t_fetch1):
            for it in coal:
                it.t_fetch_start, it.t_fetch_end = t_fetch0, t_fetch1
            if err is not None and hybrid:
                # the sync retry path can't re-run a hybrid program
                # (no sparse-operand slot) — deliver the fault
                _fail(err)
                return
            if err is None:
                # drain-thread stamps: dispatch-submit (t0) .. transfer-
                # complete (t_fetch1), minus the sampled-memcpy EWMA for
                # this result size = attributed device residency with
                # ZERO added syncs — the drain blocked on this handle's
                # D2H anyway
                dev_s, mem_s = kernelscope.attribute(
                    t_fetch1 - t0, kernelscope.result_nbytes(res))
                _attribute(dev_s, mem_s, "drain")
                _finish(res)
                return
            # the device batch (or its D2H drain) faulted on the
            # transfer thread: retry ONCE through the sync path — the
            # queries are still host-resident, so a transient device
            # fault costs one re-dispatch, not client errors. The retry
            # is a FULL device dispatch, so it runs on its own
            # short-lived thread: blocking here would stall every other
            # in-flight batch's D2H behind one faulted batch.

            def _retry_path():
                res2 = _retry_once(err)
                if res2 is not None:
                    # the retry served through the sync path: wall
                    # attribution (the drain stamps belong to the
                    # faulted attempt, not this result)
                    _attribute(time.perf_counter() - t0, 0.0, "wall")
                    _finish(res2)

            threading.Thread(target=_retry_path, daemon=True,
                             name="batcher-fault-retry").start()

        try:
            tp = self._ensure_transfer()
            if tp.inflight > 0:
                self.overlapped_dispatches += 1
                batcher_overlapped.inc()
            tp.submit(handle, _complete, ctx=ctx)
        except Exception as e:  # noqa: BLE001 — stopped mid-shutdown
            _fail(e)

    @staticmethod
    def _deliver(coal: list[_Pending], ids, dists, t1: float):
        """Route one batch's host results to their waiters (identical
        slicing for the sync and pipelined paths — parity by
        construction)."""
        for row, it in enumerate(coal):
            it.t_exec_end = t1
            kk = min(it.k, ids.shape[1])
            it.ids = ids[row, :kk]
            it.dists = dists[row, :kk]
            it.event.set()
