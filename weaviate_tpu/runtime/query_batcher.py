"""Server-side dynamic query batching.

Round-1 gap (VERDICT item 6): each concurrent Search dispatched its own
device program, so N clients paid N host->device round trips while the
scan kernel itself amortizes perfectly over a query batch
(`FlatIndex.search_by_vector_batch` runs one matmul for B queries).

Design (continuous batching, not a fixed window): a request that finds
the device idle dispatches IMMEDIATELY — zero added latency for a lone
client. Requests that arrive while a dispatch is in flight queue up; the
worker drains the whole queue into ONE batched dispatch as soon as the
device frees up. Under load the batch size self-tunes to the arrival
rate, exactly like continuous batching in model serving.

Only unfiltered requests coalesce: the scan kernel applies one validity
mask per dispatch, so a request with an AllowList mask dispatches alone
(the reference's filtered searches take a different path too —
flat_search_cutoff). Mixed k's batch together at max(k) and slice.
"""

from __future__ import annotations

import threading

import numpy as np


class _Pending:
    __slots__ = ("query", "k", "allow", "event", "ids", "dists", "error")

    def __init__(self, query, k, allow):
        self.query = query
        self.k = k
        self.allow = allow
        self.event = threading.Event()
        self.ids = None
        self.dists = None
        self.error: Exception | None = None


class QueryBatcher:
    """Wraps one vector index's batched search entry point.

    ``batch_fn(queries [B,d], k, allow) -> (ids [B,k], dists [B,k])``.
    """

    def __init__(self, batch_fn, max_batch: int = 256):
        self._batch_fn = batch_fn
        self.max_batch = max_batch
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: list[_Pending] = []
        self._worker: threading.Thread | None = None
        self._stopped = False
        # observability (tools/bench_e2e asserts coalescing happens)
        self.dispatches = 0
        self.batched_queries = 0

    def _ensure_worker(self):
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, name="query-batcher", daemon=True)
            self._worker.start()

    def stop(self):
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    def search(self, query: np.ndarray, k: int,
               allow: np.ndarray | None = None):
        """Blocking per-request entry; coalesces under concurrency."""
        item = _Pending(np.asarray(query, dtype=np.float32), k, allow)
        with self._cv:
            self._queue.append(item)
            self._ensure_worker()
            self._cv.notify()
        item.event.wait()
        if item.error is not None:
            raise item.error
        return item.ids, item.dists

    # -- worker ---------------------------------------------------------------

    def _run(self):
        while True:
            with self._cv:
                while not self._queue and not self._stopped:
                    self._cv.wait(timeout=1.0)
                if self._stopped:
                    for it in self._queue:
                        it.error = RuntimeError("query batcher stopped")
                        it.event.set()
                    self._queue.clear()
                    return
                drained = self._queue[: self.max_batch]
                del self._queue[: len(drained)]
            try:
                from weaviate_tpu.runtime.metrics import batcher_batch_size

                batcher_batch_size.observe(len(drained))
                self._dispatch(drained)
            except Exception as e:  # noqa: BLE001 — deliver to every waiter
                for it in drained:
                    if not it.event.is_set():
                        it.error = e
                        it.event.set()

    def _dispatch(self, drained: list[_Pending]):
        # filtered requests run alone (one mask per device dispatch);
        # unfiltered requests coalesce into one batched program
        plain = [it for it in drained if it.allow is None]
        masked = [it for it in drained if it.allow is not None]
        for it in masked:
            try:
                ids, dists = self._batch_fn(it.query[None, :], it.k, it.allow)
                it.ids, it.dists = ids[0], dists[0]
            except Exception as e:  # noqa: BLE001
                it.error = e
            it.event.set()
        if not plain:
            return
        k_max = max(it.k for it in plain)
        queries = np.stack([it.query for it in plain])
        self.dispatches += 1
        self.batched_queries += len(plain)
        try:
            ids, dists = self._batch_fn(queries, k_max, None)
        except Exception as e:  # noqa: BLE001
            for it in plain:
                it.error = e
                it.event.set()
            return
        for row, it in enumerate(plain):
            it.ids = ids[row, : it.k]
            it.dists = dists[row, : it.k]
            it.event.set()
