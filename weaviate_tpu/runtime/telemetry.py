"""Usage telemetry (opt-out phone-home).

Reference: usecases/telemetry/telemetry.go:53 — INIT on startup, UPDATE
every 24h, TERMINATE on shutdown; payload is machine id + version +
object count + OS/arch; DISABLE_TELEMETRY opts out. This environment has
no egress, so pushes fail soft (logged once, never raised) — the
subsystem's value here is parity of surface and the local payload
builder, which the nodes/meta endpoints reuse.
"""

from __future__ import annotations

import json
import logging
import os
import platform
import threading
import time
import urllib.request
import uuid

logger = logging.getLogger(__name__)

DEFAULT_ENDPOINT = "https://telemetry.weaviate.io/weaviate-telemetry"

INIT = "INIT"
UPDATE = "UPDATE"
TERMINATE = "TERMINATE"


def disabled(env=os.environ) -> bool:
    return env.get("DISABLE_TELEMETRY", "").lower() in ("true", "1", "on")


class Telemeter:
    def __init__(self, db, version: str = "dev",
                 endpoint: str | None = None,
                 interval: float = 24 * 3600.0,
                 data_dir: str | None = None):
        self.db = db
        self.version = version
        self.endpoint = endpoint if endpoint is not None else \
            os.environ.get("TELEMETRY_ENDPOINT", DEFAULT_ENDPOINT)
        self.interval = interval
        # stable across restarts when a data dir is given (reference
        # persists the machine id; a fresh uuid per boot would make every
        # restart look like a new installation)
        self.machine_id = self._load_machine_id(data_dir)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._warned = False

    @staticmethod
    def _load_machine_id(data_dir: str | None) -> str:
        if not data_dir:
            return str(uuid.uuid4())
        path = os.path.join(data_dir, "machine_id")
        try:
            with open(path) as f:
                mid = f.read().strip()
            if mid:
                return mid
        except OSError:
            pass
        mid = str(uuid.uuid4())
        try:
            os.makedirs(data_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(mid + "\n")
            os.replace(tmp, path)  # atomic: concurrent boots agree
        except OSError as e:
            logger.info("machine id not persisted (%s); using an "
                        "ephemeral one", e)
        return mid

    def build_payload(self, payload_type: str) -> dict:
        """Reference payload shape (telemetry.go buildPayload)."""
        try:
            num_objects = sum(
                self.db.get_collection(c).object_count()
                for c in self.db.list_collections())
        except Exception:
            num_objects = 0
        return {
            "machineId": self.machine_id,
            "type": payload_type,
            "version": self.version,
            "numberObjects": num_objects,
            "os": platform.system().lower(),
            "arch": platform.machine(),
            "timestamp": time.time(),
        }

    def _push(self, payload_type: str) -> bool:
        payload = self.build_payload(payload_type)
        try:
            req = urllib.request.Request(
                self.endpoint, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10):
                return True
        except Exception as e:
            if not self._warned:
                logger.info("telemetry push failed (will not retry "
                            "loudly): %s", e)
                self._warned = True
            return False

    def start(self) -> None:
        if disabled() or self._thread is not None:
            return

        # INIT rides the background thread too: a hanging/unreachable
        # telemetry endpoint must never stall server startup
        def loop():
            self._push(INIT)
            while not self._stop.wait(self.interval):
                self._push(UPDATE)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="telemetry")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        # TERMINATE is fired from a daemon thread so shutdown never blocks
        # on a dead endpoint
        threading.Thread(target=self._push, args=(TERMINATE,),
                         daemon=True, name="telemetry-term").start()
        self._thread.join(1.0)
        self._thread = None
