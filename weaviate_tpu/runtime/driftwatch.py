"""Driftwatch: online recall & perf drift detection (ISSUE 19).

The three landed observability planes attribute what happened — tracing
(per-request spans), tailboard (phase timelines + SLOs), kernelscope
(device-time truth). Nothing *watches for change*: perf gating lives in
the offline benchkeeper loop and recall is never measured in
production, so an IVF drift-retrain, epoch compaction, quantization
upgrade or kernel regression can degrade answers with zero signal
(ROADMAP item 1c: the r05 flat b=64 121k->40k QPS collapse had no
in-process witness). Driftwatch is the fourth plane — three legs, all
driven from one cyclemanager callback, bound by the tailboard-era hard
rule: NO host sync on unsampled serving paths (everything here runs on
the maintenance cycle, never inline with a request).

Leg 1 — serving-path canaries. Per vector index the shard registers a
canary: a small deterministic probe set (fixed-seed sample of the
shard's own corpus; ``WEAVIATE_TPU_DRIFT_SEED``) whose host-exact
ground truth is recomputed ONLY when the corpus epoch token changes
(insert/delete/seal/compact). Each cycle the probes run *through the
real query batcher* — the same coalescing, dispatch, faultline point
and kernelscope attribution as user traffic, not a side channel —
measuring recall@10 against the sealed ground truth, attributed
device-ms (kernelscope residency delta over the probe window; shared
with concurrent traffic, hence the wide default band) and queue_wait
(wall minus residency). A recall drop or residency excursion past its
band is a typed finding.

Leg 2 — live telemetry drift. Kernelscope's per-(kind, B-bucket,
k-bucket) residency EWMAs, the memcpy EWMA, batcher overlap counters
and compile-cache events are folded into a synthetic bench-shaped run
(``{"sections": {"live": ...}}``) and compared against a
fingerprint-scoped benchkeeper baseline with
``tools.benchkeeper.core.compare`` — the SAME band math, verdict
statuses (pass/regression/stale/missing) and cross-fingerprint REFUSAL
as the CLI. The baseline is either explicit
(``WEAVIATE_TPU_DRIFT_BASELINE``) or self-sealed: once a variant has
``WEAVIATE_TPU_DRIFT_MIN_SAMPLES`` dispatches its EWMA level is sealed
as the reference (persisted to ``<data_dir>/driftwatch/
live_baseline.json`` so restarts keep comparing against the same
bands). Divergence from the CLI gate, on purpose: only ``regression``
findings flip health — a serving node legitimately has unexercised
variants after a restart (``missing``) and an unexplained improvement
(``stale``) is visible but not an incident.

Leg 3 — verdict plane + forensics. ``GET /v1/debug/drift`` serves
per-finding verdicts, trend deltas and canary history; gauges
``weaviate_tpu_drift_gate_ok`` / ``weaviate_tpu_drift_findings_total
{leg,kind}`` / ``weaviate_tpu_canary_recall{collection,shard}`` ride
the normal scrape. A finding flipping open marks ``drift:<leg>``
unhealthy in the component-health registry — which triggers the
tailboard flight-recorder snapshot via the existing
``on_component_unhealthy`` hook — and clears it when the finding
closes. Every cycle appends one JSONL record to a size-ringed history
under ``<data_dir>/driftwatch/`` that ``python -m tools.driftwatch``
can replay offline against any baseline.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from collections import deque

import numpy as np

logger = logging.getLogger(__name__)

_lock = threading.RLock()

#: canary recall depth — recall@10 is the repo-wide quality metric
#: (bench flat_headline / ivf_ann gate on it too)
CANARY_K = 10

#: a variant whose EWMA sits more than this factor above its latest
#: sample is still decaying from a cold-compile dispatch (compile rides
#: the first timed window: 100-500x a steady sample, vs 2-3x run-to-run
#: wall noise) — sealing then would freeze the inflated level as the
#: band and mask every regression below it
_SEAL_CONVERGED_RATIO = 8.0

# -- config (lazy env reads, cached; configure()/reset_for_tests drop) --------

_enabled_cached: bool | None = None
_forced: bool | None = None
_data_dir: str | None = None
_interval_forced: float | None = None


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.lower() in ("true", "1", "on", "enabled")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def enabled() -> bool:
    global _enabled_cached
    if _forced is not None:
        return _forced
    if _enabled_cached is None:
        _enabled_cached = _env_flag("WEAVIATE_TPU_DRIFTWATCH", True)
    return _enabled_cached


def interval_s() -> float:
    if _interval_forced is not None:
        return _interval_forced
    return _env_float("WEAVIATE_TPU_DRIFT_INTERVAL_S", 30.0)


def set_data_dir(path: str | None) -> None:
    """Follow the most recently opened database's data dir (the
    tailboard discipline) so embedded/test use gets on-disk history
    without Server wiring."""
    global _data_dir
    _data_dir = path


def configure(data_dir: str | None = None, enabled: bool | None = None,
              interval: float | None = None) -> None:
    """Server-start wiring: pin the data dir (history ring + sealed
    baseline live under ``<data_dir>/driftwatch``), force enable/disable
    past the env default, override the cycle interval."""
    global _forced, _interval_forced
    if data_dir is not None:
        set_data_dir(data_dir)
    if enabled is not None:
        _forced = bool(enabled)
    if interval is not None:
        _interval_forced = float(interval)


def _seed() -> int:
    return _env_int("WEAVIATE_TPU_DRIFT_SEED", 1069)


def _probe_count() -> int:
    return max(1, _env_int("WEAVIATE_TPU_DRIFT_PROBES", 8))


def _recall_band() -> float:
    """ABSOLUTE recall@10 drop vs the sealed reference that opens a
    canary finding (recall is bounded in [0,1]; a fractional band of a
    0.99 reference would be numerically the same thing)."""
    return _env_float("WEAVIATE_TPU_DRIFT_RECALL_BAND", 0.05)


def _residency_band() -> float:
    """Fractional canary device-ms excursion vs the sealed reference.
    Wide by default: the probe window's kernelscope residency delta is
    shared with concurrent traffic."""
    return _env_float("WEAVIATE_TPU_DRIFT_RESIDENCY_BAND", 3.0)


def _live_band() -> float:
    """Band written into self-sealed live-baseline entries (an explicit
    WEAVIATE_TPU_DRIFT_BASELINE carries its own per-entry bands)."""
    return _env_float("WEAVIATE_TPU_DRIFT_LIVE_BAND", 0.75)


def _min_samples() -> int:
    return max(1, _env_int("WEAVIATE_TPU_DRIFT_MIN_SAMPLES", 3))


def _max_corpus_rows() -> int:
    """Host-exact ground truth is O(rows x dim) host BLAS per probe
    reseal; past this row count the canary marks itself skipped instead
    of burning the maintenance thread."""
    return _env_int("WEAVIATE_TPU_DRIFT_CANARY_MAX_ROWS", 262_144)


def _history_cap_bytes() -> int:
    return _env_int("WEAVIATE_TPU_DRIFT_HISTORY_BYTES", 4 * 1024 * 1024)


# -- leg 1: serving-path canaries ---------------------------------------------


class _Canary:
    """One registered probe target (a shard's vector space).

    ``search_fn(queries[P,d], k) -> list[np.ndarray] | None`` must route
    through the REAL query batcher; ``corpus_fn() -> (doc_ids[N],
    vectors[N,d]) | None`` returns host-resident truth vectors;
    ``epoch_token_fn() -> hashable`` changes iff the corpus changed;
    ``pairwise_fn(qs, vecs) -> [B,N]`` is the index's own host-exact
    distance (metric-correct ground truth without driftwatch knowing
    metrics)."""

    __slots__ = ("key", "collection", "shard", "search_fn", "corpus_fn",
                 "epoch_token_fn", "pairwise_fn", "token", "probe_ids",
                 "probe_vecs", "gt", "ref_recall", "ref_device_ms",
                 "skipped", "last", "history")

    def __init__(self, key, collection, shard, search_fn, corpus_fn,
                 epoch_token_fn, pairwise_fn):
        self.key = key
        self.collection = collection
        self.shard = shard
        self.search_fn = search_fn
        self.corpus_fn = corpus_fn
        self.epoch_token_fn = epoch_token_fn
        self.pairwise_fn = pairwise_fn
        self.token = None
        self.probe_ids = None   # np.int64 [P] — WHICH corpus rows probe
        self.probe_vecs = None  # np.float32 [P, d]
        self.gt = None          # list of np.int64 arrays (<=CANARY_K each)
        self.ref_recall = None
        self.ref_device_ms = None
        self.skipped: str | None = None
        self.last: dict | None = None
        self.history: deque = deque(maxlen=64)


_canaries: dict[str, _Canary] = {}


def register_canary(key: str, *, collection: str = "", shard: str = "",
                    search_fn, corpus_fn, epoch_token_fn,
                    pairwise_fn) -> None:
    """Idempotent (re)registration — a shard re-opening its index under
    the same key replaces the target and its sealed state."""
    with _lock:
        _canaries[key] = _Canary(key, collection, shard, search_fn,
                                 corpus_fn, epoch_token_fn, pairwise_fn)


def unregister_canaries(prefix: str) -> None:
    """Drop every canary whose key starts with ``prefix`` (shard close:
    ``<collection>/<shard>/``)."""
    with _lock:
        for k in [k for k in _canaries if k.startswith(prefix)]:
            del _canaries[k]


def _probe_rng(key: str) -> np.random.Generator:
    """Deterministic per-target RNG: the fixed seed XOR a stable hash of
    the key (zlib.crc32, NOT hash() — PYTHONHASHSEED would break the
    same-probe-set-across-restarts guarantee)."""
    return np.random.default_rng(
        (_seed() ^ zlib.crc32(key.encode())) & 0xFFFFFFFF)


def _seal_canary(c: _Canary, token) -> None:
    """Recompute probe set + host-exact ground truth. Called ONLY on
    corpus-epoch change (and first sight) — this is the one place
    driftwatch does O(corpus) host work, off the serving path."""
    c.token = token
    c.skipped = None
    c.gt = None
    c.ref_recall = None
    c.ref_device_ms = None
    corpus = c.corpus_fn()
    if corpus is None:
        c.skipped = "no host corpus (index without doc map or empty)"
        return
    ids, vecs = corpus
    ids = np.asarray(ids, dtype=np.int64)
    vecs = np.asarray(vecs, dtype=np.float32)
    n = len(ids)
    if n == 0:
        c.skipped = "empty corpus"
        return
    if n > _max_corpus_rows():
        c.skipped = (f"corpus {n} rows over WEAVIATE_TPU_DRIFT_CANARY_"
                     f"MAX_ROWS={_max_corpus_rows()} — host-exact ground "
                     "truth skipped")
        return
    rng = _probe_rng(c.key)
    # sample over the SORTED id order so the probe set is a pure
    # function of (seed, key, corpus content) — never of insert order
    order = np.argsort(ids, kind="stable")
    sel = rng.choice(n, size=min(_probe_count(), n), replace=False)
    sel = np.sort(sel)
    rows = order[sel]
    c.probe_ids = ids[rows]
    c.probe_vecs = vecs[rows]
    k = min(CANARY_K, n)
    d = np.asarray(c.pairwise_fn(c.probe_vecs, vecs), dtype=np.float64)
    top = np.argsort(d, axis=1, kind="stable")[:, :k]
    c.gt = [ids[top[i]] for i in range(len(rows))]


def _run_canary(c: _Canary) -> tuple[dict, list[dict]]:
    """One canary cycle: reseal on epoch change, run probes through the
    serving batcher, classify. Returns (cycle record, findings)."""
    try:
        token = c.epoch_token_fn()
    except Exception as e:  # a closing shard must not kill the cycle
        return {"key": c.key, "skipped": f"epoch token failed: {e}"}, []
    if c.gt is None or token != c.token:
        try:
            _seal_canary(c, token)
        except Exception as e:
            c.skipped = f"ground-truth seal failed: {e}"
    rec = {"key": c.key, "collection": c.collection, "shard": c.shard}
    if c.skipped is not None:
        rec["skipped"] = c.skipped
        return rec, []

    from weaviate_tpu.runtime import kernelscope

    dev0 = kernelscope.total_device_seconds()
    t0 = time.perf_counter()
    try:
        got = c.search_fn(c.probe_vecs, CANARY_K)
    except Exception as e:
        rec["skipped"] = f"probe search failed: {e}"
        return rec, []
    wall_ms = (time.perf_counter() - t0) * 1000.0
    device_ms = max(
        0.0, (kernelscope.total_device_seconds() - dev0) * 1000.0)
    if got is None:
        rec["skipped"] = "index has no batched serving path"
        return rec, []
    hits = 0
    denom = 0
    for want, have in zip(c.gt, got):
        want_set = set(np.asarray(want).tolist())
        have_ids = set(np.asarray(have)[:CANARY_K].tolist())
        hits += len(want_set & have_ids)
        denom += len(want_set)
    recall = (hits / denom) if denom else 0.0
    queue_wait_ms = max(0.0, wall_ms - device_ms)
    if c.ref_recall is None:
        # reference sealed at the first run after a ground-truth
        # (re)compute: the canary watches for CHANGE from here on
        c.ref_recall = recall
        c.ref_device_ms = device_ms
    rec.update(recall=round(recall, 4), ref_recall=round(c.ref_recall, 4),
               wall_ms=round(wall_ms, 3), device_ms=round(device_ms, 3),
               ref_device_ms=round(c.ref_device_ms, 3),
               queue_wait_ms=round(queue_wait_ms, 3),
               probes=len(c.gt))
    findings = []
    drop = c.ref_recall - recall
    if drop > _recall_band():
        findings.append({
            "key": f"canary:{c.key}:recall", "leg": "canary",
            "kind": "recall", "flips_health": True,
            "value": round(recall, 4), "baseline": round(c.ref_recall, 4),
            "delta_frac": round(drop, 4),
            "reason": (f"canary recall@{CANARY_K} dropped {drop:.3f} "
                       f"below the sealed reference {c.ref_recall:.3f} "
                       f"(band {_recall_band():.3f}) — answers degraded "
                       "on the live serving path"),
        })
    # same normalized-delta band math as benchkeeper (direction
    # "lower": positive delta = regressing)
    if c.ref_device_ms > 1e-6:
        delta = (device_ms - c.ref_device_ms) / c.ref_device_ms
        if delta > _residency_band():
            findings.append({
                "key": f"canary:{c.key}:residency", "leg": "canary",
                "kind": "residency", "flips_health": True,
                "value": round(device_ms, 3),
                "baseline": round(c.ref_device_ms, 3),
                "delta_frac": round(delta, 4),
                "reason": (f"canary probe residency {device_ms:.2f}ms "
                           f"regressed {delta * 100:.0f}% beyond the ±"
                           f"{_residency_band() * 100:.0f}% band vs the "
                           f"sealed {c.ref_device_ms:.2f}ms reference"),
            })
    c.last = rec
    c.history.append({"t": time.time(), "recall": rec["recall"],
                      "device_ms": rec["device_ms"],
                      "queue_wait_ms": rec["queue_wait_ms"]})
    return rec, findings


# -- leg 2: live telemetry vs benchkeeper bands -------------------------------

_live_baseline: dict | None = None
_live_baseline_source: str | None = None
_live_baseline_error: str | None = None
_prev_counters: dict[str, float] = {}
_last_verdict: dict | None = None


def live_fingerprint() -> dict:
    """The environment this node's live telemetry was measured in —
    the same keys benchkeeper baselines name, so an explicit TPU-rig
    baseline REFUSES comparison on a CPU node instead of gating noise."""
    try:
        import jax

        return {"jax": jax.__version__,
                "platform": jax.default_backend(),
                "device_count": jax.device_count()}
    except Exception:
        return {"platform": "unknown"}


def _counter_value(child) -> float:
    try:
        return float(child.value)
    except Exception:
        return 0.0


def live_section() -> dict:
    """The synthetic bench section driftwatch classifies: kernelscope's
    per-variant residency EWMAs, the memcpy estimator, and per-cycle
    counter deltas (compile-cache misses, batcher overlap). Counter
    deltas are exported ``_p1`` (value + 1): benchkeeper refuses a
    zero reference value, and the quiet steady state IS zero."""
    from weaviate_tpu.runtime import kernelscope
    from weaviate_tpu.runtime.metrics import (batcher_overlapped,
                                              compile_cache_events)

    ks = kernelscope.snapshot()
    residency = {variant: {"ewma_ms": v.get("ewma_ms"),
                           "last_ms": v.get("last_ms"),
                           "n": v.get("n"), "source": v.get("source")}
                 for variant, v in ks["variants"].items()}
    sec: dict = {"residency": residency,
                 "dispatches": ks.get("dispatches", {})}
    g_us = ks["memcpy"].get("global_us")
    if g_us is not None:
        sec["memcpy"] = {"global_us": g_us,
                         "samples": ks["memcpy"].get("samples")}
    miss_total = _counter_value(compile_cache_events.labels("miss"))
    overlap_total = _counter_value(batcher_overlapped.labels())
    with _lock:
        miss_delta = miss_total - _prev_counters.get("compile_miss", 0.0)
        overlap_delta = overlap_total - _prev_counters.get("overlap", 0.0)
        _prev_counters["compile_miss"] = miss_total
        _prev_counters["overlap"] = overlap_total
    sec["counters"] = {
        "compile_miss_total": miss_total,
        "overlap_total": overlap_total,
        "compile_miss_per_cycle_p1": max(0.0, miss_delta) + 1.0,
        "overlap_per_cycle_p1": max(0.0, overlap_delta) + 1.0,
    }
    return sec


def seal_live_baseline(section: dict, fingerprint: dict) -> dict | None:
    """Self-seal a benchkeeper-shaped baseline from the current live
    telemetry: one ``kind: device`` entry per residency variant with
    enough samples, the memcpy level, and the compile-storm detector.
    Returns None when nothing is warm enough to seal yet."""
    entries = []
    for variant, v in sorted(section.get("residency", {}).items()):
        ewma = v.get("ewma_ms")
        if (v.get("n") or 0) < _min_samples() or not ewma \
                or ewma <= 1e-6:
            continue
        last = v.get("last_ms")
        if last and float(ewma) > float(last) * _SEAL_CONVERGED_RATIO:
            continue
        entries.append({
            "id": f"live.residency.{variant}",
            "section": "live",
            "metric": f"residency.{variant}.ewma_ms",
            "value": round(float(ewma), 4), "band": _live_band(),
            "direction": "lower", "kind": "device", "unit": "ms",
            "reason": (f"self-sealed residency EWMA for compiled variant "
                       f"{variant} after {v.get('n')} dispatches — a "
                       "drift past the band is a kernel/runtime "
                       "regression on the live serving path (the "
                       "in-process witness ROADMAP 1c asks for)"),
        })
    if not entries:
        return None
    g_us = (section.get("memcpy") or {}).get("global_us")
    if g_us:
        entries.append({
            "id": "live.memcpy.global_us",
            "section": "live", "metric": "memcpy.global_us",
            "value": round(float(g_us), 2), "band": _live_band(),
            "direction": "lower", "kind": "device", "unit": "us",
            "reason": "self-sealed sampled-memcpy EWMA — a drift means "
                      "D2H transfer cost moved (PCIe/tunnel change or "
                      "attribution bug), which silently skews every "
                      "drain-source residency number",
        })
    entries.append({
        "id": "live.compile_miss_per_cycle",
        "section": "live",
        "metric": "counters.compile_miss_per_cycle_p1",
        "value": 1.0, "band": 2.0,
        "direction": "lower", "kind": "wall", "unit": "events",
        "reason": "compile-storm detector: steady state recompiles "
                  "nothing per cycle (p1 metric = misses + 1, benchkeeper "
                  "refuses a zero reference). More than two persistent-"
                  "cache misses in one cycle means the bounded pow2 "
                  "variant set broke (shape leak) or the cache is gone — "
                  "each miss is seconds of serving-thread stall",
    })
    return {
        "notes": "self-sealed by runtime/driftwatch.py from live "
                 "telemetry — replayable offline via python -m "
                 "tools.driftwatch",
        "sealed_at": time.time(),
        "fingerprint": {k: fingerprint[k]
                        for k in ("platform", "jax") if k in fingerprint},
        "entries": entries,
    }


def _baseline_dir() -> str | None:
    return os.path.join(_data_dir, "driftwatch") if _data_dir else None


def _sealed_baseline_path() -> str | None:
    d = _baseline_dir()
    return os.path.join(d, "live_baseline.json") if d else None


def _ensure_live_baseline(section: dict, fingerprint: dict):
    """Resolve the live-leg baseline: explicit env path > previously
    sealed on-disk file > seal now from warm telemetry. Validation and
    persistence both reuse benchkeeper's code."""
    global _live_baseline, _live_baseline_source, _live_baseline_error
    with _lock:
        if _live_baseline is not None:
            return _live_baseline
    from tools.benchkeeper import core as bk

    env_path = os.environ.get("WEAVIATE_TPU_DRIFT_BASELINE", "")
    if env_path:
        try:
            base = bk.load_baseline(env_path)
            src, err = f"env:{env_path}", None
        except bk.BaselineError as e:
            base, src, err = None, None, str(e)
    else:
        base, src, err = None, None, None
        path = _sealed_baseline_path()
        if path and os.path.exists(path):
            try:
                base = bk.load_baseline(path)
                src = f"sealed:{path}"
            except bk.BaselineError as e:
                err = str(e)  # corrupt seal: reseal below
        if base is None:
            sealed = seal_live_baseline(section, fingerprint)
            if sealed is not None:
                try:
                    bk.validate_baseline(sealed, "<driftwatch-seal>")
                except bk.BaselineError as e:
                    sealed, err = None, str(e)
            if sealed is not None:
                base, src, err = sealed, "sealed:memory", None
                if path:
                    try:
                        bk._atomic_write_json(path, sealed)
                        src = f"sealed:{path}"
                    except OSError:
                        pass  # memory seal still classifies
    with _lock:
        _live_baseline = base
        _live_baseline_source = src
        _live_baseline_error = err
    return base


def classify_live(section: dict, baseline: dict,
                  fingerprint: dict | None = None) -> dict:
    """Classify one live-telemetry section against a benchkeeper
    baseline — literally ``tools.benchkeeper.core.compare`` on a
    synthetic one-section run, so verdict statuses and the
    cross-fingerprint refusal are benchkeeper's own (the parity the
    tests pin)."""
    from tools.benchkeeper import core as bk

    run = {"env_fingerprint": fingerprint or live_fingerprint(),
           "sections": {"live": section}}
    return bk.compare(run, baseline)


def _live_findings(verdict: dict) -> list[dict]:
    """Typed findings from a live verdict. Only ``regression`` flips
    health (see the module docstring for why stale/missing do not)."""
    out = []
    if verdict.get("refused"):
        out.append({
            "key": "live:fingerprint:refused", "leg": "live",
            "kind": "refused", "flips_health": False,
            "reason": ("live comparison refused — "
                       + verdict["refused"]["reason"] + ": "
                       + "; ".join(verdict["refused"]["mismatched"])),
        })
        return out
    for row in verdict.get("entries", ()):
        status = row.get("status")
        if status in ("regression", "stale"):
            out.append({
                "key": f"live:{row['id']}:{status}", "leg": "live",
                "kind": status, "flips_health": status == "regression",
                "value": row.get("value"), "baseline": row.get("baseline"),
                "delta_frac": row.get("delta_frac"),
                "reason": row.get("gate_reason") or row.get("reason"),
            })
    return out


# -- leg 3: verdict plane, health flips, history ring -------------------------

_findings: dict[str, dict] = {}     # open findings, keyed by finding key
_health_flipped: set[str] = set()   # drift:<leg> components WE marked
_cycle_seq = 0
_last_cycle_t: float | None = None


def history_path() -> str | None:
    d = _baseline_dir()
    return os.path.join(d, "history.jsonl") if d else None


def _append_history(record: dict) -> None:
    """One JSONL line per cycle, size-ringed: past the byte cap the file
    rotates to ``history.jsonl.1`` (one generation) so the ring is
    durable without growing without bound."""
    path = history_path()
    if path is None:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        try:
            if os.path.getsize(path) > _history_cap_bytes():
                os.replace(path, path + ".1")
        except OSError:
            pass
        with open(path, "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError:
        pass  # forensics must never fail the cycle


def _publish_gauges(gate_ok: bool) -> None:
    try:
        from weaviate_tpu.runtime.metrics import drift_gate_ok

        drift_gate_ok.set(1.0 if gate_ok else 0.0)
    except Exception:
        pass


def _publish_canary_recall(records: list[dict]) -> None:
    """weaviate_tpu_canary_recall{collection,shard}: the WORST recall
    across a shard's vector spaces this cycle (one series per shard)."""
    worst: dict[tuple[str, str], float] = {}
    for r in records:
        if "recall" not in r:
            continue
        key = (r.get("collection") or "-", r.get("shard") or "-")
        worst[key] = min(worst.get(key, 1.0), r["recall"])
    if not worst:
        return
    try:
        from weaviate_tpu.runtime.metrics import canary_recall

        for (col, shard), rec in worst.items():
            canary_recall.labels(col, shard).set(rec)
    except Exception:
        pass


def _apply_findings(new: dict[str, dict]) -> bool:
    """Transition bookkeeping: count newly opened findings, flip/clear
    ``drift:<leg>`` component health (the flip triggers the tailboard
    flight-recorder snapshot through degrade's existing hook). Returns
    the gate verdict."""
    from weaviate_tpu.runtime import degrade

    now = time.time()
    with _lock:
        opened = [f for k, f in new.items() if k not in _findings]
        for k, f in new.items():
            f["since"] = _findings[k]["since"] if k in _findings else now
        _findings.clear()
        _findings.update(new)
        flips = {}
        for f in new.values():
            if f.get("flips_health"):
                flips.setdefault(f["leg"], f["reason"])
        flipped = set(_health_flipped)
    if opened:
        try:
            from weaviate_tpu.runtime.metrics import drift_findings_total

            for f in opened:
                drift_findings_total.labels(f["leg"], f["kind"]).inc()
        except Exception:
            pass
    for leg, reason in flips.items():
        degrade.mark_unhealthy(f"drift:{leg}", reason)
        with _lock:
            _health_flipped.add(f"drift:{leg}")
    for comp in flipped:
        if comp.removeprefix("drift:") not in flips:
            degrade.mark_healthy(comp)
            with _lock:
                _health_flipped.discard(comp)
    return not flips


def run_cycle() -> bool:
    """The cyclemanager callback (and the deterministic test entry):
    run every canary, classify live telemetry, apply findings, append
    the history record. Returns whether any leg produced work (False =
    disabled or nothing registered, letting the cycle back off)."""
    global _cycle_seq, _last_cycle_t, _last_verdict
    if not enabled():
        return False
    with _lock:
        targets = list(_canaries.values())
        _cycle_seq += 1
        seq = _cycle_seq
    canary_records: list[dict] = []
    new_findings: dict[str, dict] = {}
    for c in targets:
        rec, found = _run_canary(c)
        canary_records.append(rec)
        for f in found:
            new_findings[f["key"]] = f
    fp = live_fingerprint()
    section = live_section()
    verdict_summary = None
    classified = False
    try:
        baseline = _ensure_live_baseline(section, fp)
    except Exception as e:  # tools/ stripped from the install
        baseline = None
        with _lock:
            global _live_baseline_error
            _live_baseline_error = f"benchkeeper unavailable: {e}"
    if baseline is not None:
        verdict = classify_live(section, baseline, fp)
        classified = True
        with _lock:
            _last_verdict = verdict
        for f in _live_findings(verdict):
            new_findings[f["key"]] = f
        verdict_summary = {
            "ok": verdict["ok"],
            "refused": bool(verdict.get("refused")),
            "checked": verdict["checked"], "passed": verdict["passed"],
            "regressions": verdict["regressions"],
            "stale": verdict["stale"], "missing": verdict["missing"],
        }
    gate_ok = _apply_findings(new_findings)
    _publish_gauges(gate_ok)
    _publish_canary_recall(canary_records)
    with _lock:
        _last_cycle_t = time.time()
        findings_out = list(_findings.values())
    _append_history({
        "t": time.time(), "cycle": seq, "gate_ok": gate_ok,
        "fingerprint": fp,
        "canaries": canary_records,
        "live": {"metrics": section, "verdict": verdict_summary,
                 "baseline_source": _live_baseline_source},
        "findings": findings_out,
    })
    ran = bool(targets) or classified
    return ran


# -- debug / scrape surface ---------------------------------------------------


def snapshot() -> dict:
    """The ``GET /v1/debug/drift`` payload: gate verdict, open findings,
    per-entry trend deltas from the last live verdict, canary state +
    history, and where the forensics live."""
    with _lock:
        findings = [dict(f) for f in _findings.values()]
        verdict = _last_verdict
        canaries = {
            c.key: {
                "collection": c.collection, "shard": c.shard,
                "skipped": c.skipped,
                "probe_doc_ids": (None if c.probe_ids is None
                                  else c.probe_ids.tolist()),
                "epoch_token": (None if c.token is None
                                else str(c.token)),
                "ref_recall": c.ref_recall,
                "ref_device_ms": c.ref_device_ms,
                "last": c.last,
                "history": list(c.history),
            } for c in _canaries.values()}
        seq, last_t = _cycle_seq, _last_cycle_t
        src, err = _live_baseline_source, _live_baseline_error
    gate_ok = not any(f.get("flips_health") for f in findings)
    trends = []
    if verdict and not verdict.get("refused"):
        trends = [{"id": r["id"], "status": r.get("status"),
                   "value": r.get("value"), "baseline": r.get("baseline"),
                   "delta_frac": r.get("delta_frac"),
                   "band": r.get("band"), "unit": r.get("unit")}
                  for r in verdict.get("entries", ())]
    return {
        "enabled": enabled(),
        "cycle": seq,
        "lastCycleAt": last_t,
        "intervalS": interval_s(),
        "gateOk": gate_ok,
        "findings": findings,
        "canaries": canaries,
        "live": {
            "baselineSource": src,
            "baselineError": err,
            "refused": (verdict or {}).get("refused"),
            "trends": trends,
        },
        "historyPath": history_path(),
    }


def scrape_refresh() -> None:
    """Read-point hook for /v1/metrics: make the gate gauge truthful
    even before the first cycle (a node that never classified anything
    has no open findings — gate 1, not a default-0 false alarm)."""
    with _lock:
        findings = list(_findings.values())
    _publish_gauges(not any(f.get("flips_health") for f in findings))


# -- test isolation -----------------------------------------------------------


def reset_for_tests() -> None:
    """Drop every registration, sealed reference, finding and cached
    env read (conftest autouse — a sealed canary or an open drift
    finding leaking across tests would poison health assertions)."""
    global _enabled_cached, _forced, _data_dir, _interval_forced
    global _live_baseline, _live_baseline_source, _live_baseline_error
    global _last_verdict, _cycle_seq, _last_cycle_t
    with _lock:
        _canaries.clear()
        _findings.clear()
        _health_flipped.clear()
        _prev_counters.clear()
        _enabled_cached = None
        _forced = None
        _data_dir = None
        _interval_forced = None
        _live_baseline = None
        _live_baseline_source = None
        _live_baseline_error = None
        _last_verdict = None
        _cycle_seq = 0
        _last_cycle_t = None
