"""Unified retry/deadline policy: one backoff, one budget, typed errors.

Before this module every layer invented its own failure handling:
``cluster/transport.rpc`` was a single shot with a fixed timeout,
``cluster/remote`` hard-coded 30s, and nothing connected a request's
remaining time to the timeouts of the RPCs issued on its behalf — a
query could sit in retry loops long after its client gave up.

Two primitives fix that:

- **Deadline propagation.** The REST/gRPC edge opens ``deadline(budget)``
  once per request; the absolute expiry rides a contextvar through the
  query batcher, shard fan-out, replication, and every transport call
  (``tracing.propagate`` carries it onto pool threads). Layers derive
  per-attempt timeouts from ``remaining()`` — an RPC can never be given
  more time than its request has left, and ``DeadlineExceeded`` is a
  typed error the API edges map to 504/DEADLINE_EXCEEDED instead of a
  generic 500.

- **RetryPolicy.** Capped exponential backoff with FULL jitter
  (sleep ~ U(0, min(cap, base*mult^attempt)) — the AWS-analysis shape
  that decorrelates retry storms), a retriable-vs-terminal classifier,
  and deadline awareness: a retry whose backoff would outlive the
  budget raises ``DeadlineExceeded`` immediately rather than sleeping
  into a guaranteed timeout.
"""

from __future__ import annotations

import contextvars
import random
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

#: absolute expiry (time.monotonic seconds) of the current request's
#: budget; None = no deadline set (background/admin work)
_deadline_var: contextvars.ContextVar[float | None] = contextvars.ContextVar(
    "weaviate_tpu_deadline", default=None)


class DeadlineExceeded(TimeoutError):
    """The request's time budget ran out (typed: REST maps it to 504
    with code DEADLINE_EXCEEDED, gRPC to StatusCode.DEADLINE_EXCEEDED —
    never a generic 500)."""

    def __init__(self, layer: str = "", message: str = ""):
        super().__init__(message
                         or f"deadline exceeded{' in ' + layer if layer else ''}")
        self.layer = layer


class OverloadedError(RuntimeError):
    """Typed retriable overload (bounded queue full, admission refused).
    Carries the backoff hint REST surfaces as a ``Retry-After`` header
    on its 503."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


@contextmanager
def deadline(budget_s: float | None):
    """Scope a time budget. Nested budgets only ever SHRINK the window —
    an inner layer granting itself more time than its caller has left
    would defeat propagation. ``None``/non-positive = no-op."""
    if budget_s is None or budget_s <= 0:
        yield
        return
    expiry = time.monotonic() + budget_s
    outer = _deadline_var.get()
    if outer is not None:
        expiry = min(expiry, outer)
    token = _deadline_var.set(expiry)
    try:
        yield
    finally:
        _deadline_var.reset(token)


def current_deadline() -> float | None:
    """Absolute monotonic expiry, for handing across threads
    (``tracing.propagate`` captures this)."""
    return _deadline_var.get()


def set_deadline(expiry: float | None):
    """Install an absolute expiry captured elsewhere; returns the reset
    token. Worker-thread plumbing only — request code uses
    ``deadline()``."""
    return _deadline_var.set(expiry)


def reset_deadline(token) -> None:
    _deadline_var.reset(token)


def remaining() -> float | None:
    """Seconds left in the budget (may be <= 0), None when no deadline
    is set."""
    expiry = _deadline_var.get()
    if expiry is None:
        return None
    return expiry - time.monotonic()


def check(layer: str = "") -> None:
    """Raise ``DeadlineExceeded`` if the budget is spent. Call before
    starting work that is pointless to begin with no time left."""
    rem = remaining()
    if rem is not None and rem <= 0:
        _count_deadline(layer)
        raise DeadlineExceeded(layer)


def budget_timeout(default_s: float, layer: str = "") -> float:
    """Per-attempt timeout derived from the budget: ``min(default,
    remaining)``. Raises ``DeadlineExceeded`` when nothing is left —
    issuing an IO with a zero timeout just converts the typed error
    into a confusing transport one."""
    rem = remaining()
    if rem is None:
        return default_s
    if rem <= 0:
        _count_deadline(layer)
        raise DeadlineExceeded(layer)
    return min(default_s, rem)


def _count_deadline(layer: str) -> None:
    try:
        from weaviate_tpu.runtime.metrics import deadline_exceeded_total

        deadline_exceeded_total.labels(layer or "unknown").inc()
    except Exception:  # pragma: no cover
        pass


# -- classification -----------------------------------------------------------

#: HTTP-ish statuses worth another attempt: transport-level failure (0),
#: throttling, and gateway-class upstream trouble. A 4xx or a handler
#: 500 means the peer is alive and deterministic — retrying replays the
#: same failure.
RETRIABLE_STATUSES = frozenset({0, 429, 502, 503, 504})


def default_retriable(exc: BaseException) -> bool:
    """The repo-wide retriable-vs-terminal line. Circuit-open is
    TERMINAL here: the breaker already knows the peer is down, and
    burning backoff against it is exactly the budget leak breakers
    exist to stop — callers fail over to another replica instead."""
    from weaviate_tpu.cluster.transport import CircuitOpenError, RpcError

    if isinstance(exc, (DeadlineExceeded, CircuitOpenError)):
        return False
    if isinstance(exc, OverloadedError):
        return True
    if isinstance(exc, RpcError):
        # a per-attempt TIMEOUT already burned its full time ceiling —
        # retrying burns another (3 × 30s against one black-holed
        # replica before failover gets a chance). Fast transport
        # failures (refused, reset, half-dead HTTP) stay retriable;
        # slow death is the failover layers' job.
        if exc.timed_out:
            return False
        return exc.status in RETRIABLE_STATUSES
    return False


@dataclass
class RetryPolicy:
    """Capped exponential backoff with full jitter, deadline-capped.

    ``call(fn, *args, **kwargs)`` runs ``fn`` up to ``max_attempts``
    times. Terminal errors re-raise immediately; retriable ones back
    off ``U(0, min(cap, base * mult^attempt))`` seconds (an
    ``OverloadedError``'s ``retry_after_s`` floors the draw). A backoff
    that cannot fit in the remaining budget raises ``DeadlineExceeded``
    with the last error chained — the caller learns BOTH that time ran
    out and why the attempts failed."""

    max_attempts: int = 3
    base_s: float = 0.05
    cap_s: float = 2.0
    multiplier: float = 2.0
    retriable: object = staticmethod(default_retriable)
    #: seeded stream for reproducible chaos runs; None = module random
    rng: random.Random | None = field(default=None, repr=False)
    op: str = "rpc"

    def backoff_s(self, attempt: int) -> float:
        """Jittered sleep before attempt ``attempt+1`` (0-based)."""
        ceiling = min(self.cap_s, self.base_s * (self.multiplier ** attempt))
        draw = (self.rng or random).random()
        return draw * ceiling

    def call(self, fn, *args, **kwargs):
        last: BaseException | None = None
        for attempt in range(self.max_attempts):
            check(self.op)
            try:
                result = fn(*args, **kwargs)
                if attempt:
                    _count_retry(self.op, "recovered")
                return result
            except BaseException as e:
                if not self.retriable(e) \
                        or attempt == self.max_attempts - 1:
                    if attempt:
                        _count_retry(self.op, "exhausted")
                    raise
                last = e
                sleep = self.backoff_s(attempt)
                if isinstance(e, OverloadedError):
                    sleep = max(sleep, e.retry_after_s)
                rem = remaining()
                if rem is not None and sleep >= rem:
                    # the budget cannot absorb another attempt: surface
                    # the TYPED timeout (chained to the real failure)
                    # instead of sleeping into a guaranteed miss
                    _count_retry(self.op, "deadline")
                    _count_deadline(self.op)
                    raise DeadlineExceeded(
                        self.op,
                        f"deadline exhausted after {attempt + 1} "
                        f"attempt(s) of {self.op}: {e}") from e
                _count_retry(self.op, "retried")
                time.sleep(sleep)
        raise last  # pragma: no cover — loop always returns or raises


def _count_retry(op: str, outcome: str) -> None:
    try:
        from weaviate_tpu.runtime.metrics import retries_total

        retries_total.labels(op, outcome).inc()
    except Exception:  # pragma: no cover
        pass
