"""Last benchkeeper gate verdict, surfaced from the serving process.

The perf gate (tools/benchkeeper) runs out-of-process — usually in CI
or on the bench rig — and persists its verdict JSON artifact
(``last_verdict.json`` next to the baseline, or wherever
``BENCHKEEPER_VERDICT_PATH`` points). This module is the in-process
read side: ``GET /v1/debug/perf`` serves the verdict plus per-entry
trend deltas, and every load republishes the ``weaviate_tpu_bench_*``
gauges so regressions are visible from the same Prometheus surface as
the HBM ledger — not only to whoever happens to read the bench log.

Nothing here imports jax or benchkeeper; a node with no verdict on
disk reports that plainly instead of failing.
"""

from __future__ import annotations

import json
import os
import threading

_lock = threading.Lock()
_published_entries: set[tuple[str, str]] = set()  # (entry, unit) gauge keys
_refreshed: dict = {"path": None, "mtime": None}  # last published artifact


def verdict_path() -> str:
    """BENCHKEEPER_VERDICT_PATH, else the artifact next to the checked-in
    baseline (tools/benchkeeper/last_verdict.json in this checkout)."""
    env = os.environ.get("BENCHKEEPER_VERDICT_PATH")
    if env:
        return env
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tools", "benchkeeper", "last_verdict.json")


def load_verdict(path: str | None = None) -> dict | None:
    """The persisted verdict dict, or None when absent/corrupt (a bad
    artifact must not break the debug surface reporting on it)."""
    path = path or verdict_path()
    try:
        with open(path) as f:
            v = json.load(f)
    except (OSError, ValueError):
        return None
    return v if isinstance(v, dict) and "entries" in v else None


def publish_metrics(verdict: dict) -> None:
    """Republish the weaviate_tpu_bench_* gauges from a verdict. Series
    for entries that vanished from the verdict are removed, not left
    exporting stale values (same discipline as the HBM ledger gauges)."""
    from weaviate_tpu.runtime.metrics import (bench_delta_frac,
                                              bench_gate_ok,
                                              bench_gate_regressions,
                                              bench_gate_stale,
                                              bench_metric_value)

    with _lock:
        bench_gate_ok.set(1.0 if verdict.get("ok") else 0.0)
        bench_gate_regressions.set(float(verdict.get("regressions", 0)))
        bench_gate_stale.set(float(verdict.get("stale", 0)))
        live: set[tuple[str, str]] = set()
        for row in verdict.get("entries", ()):
            eid = str(row.get("id", ""))
            unit = str(row.get("unit", ""))
            if row.get("value") is not None:
                bench_metric_value.labels(eid, unit).set(
                    float(row["value"]))
                live.add((eid, unit))
            if row.get("delta_frac") is not None:
                bench_delta_frac.labels(eid).set(float(row["delta_frac"]))
        live_ids = {eid for eid, _ in live}
        for eid, unit in _published_entries - live:
            bench_metric_value.remove(eid, unit)
            # an entry whose unit merely changed is still live — only a
            # fully vanished entry drops its delta series
            if eid not in live_ids:
                bench_delta_frac.remove(eid)
        _published_entries.clear()
        _published_entries.update(live)


def refresh(path: str | None = None) -> None:
    """Republish the gauges from the on-disk verdict iff it changed
    since the last publish (mtime-cached). The metrics exposition
    handlers call this on every scrape, so a scrape-only Prometheus
    setup sees the verdict without anyone ever reading
    ``/v1/debug/perf``."""
    path = path or verdict_path()
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return
    if _refreshed["path"] == path and _refreshed["mtime"] == mtime:
        return
    verdict = load_verdict(path)
    # cache the mtime even when the artifact is corrupt — a truncated
    # file must not be re-parsed on every scrape until it changes
    _refreshed.update(path=path, mtime=mtime)
    if verdict is None:
        return
    publish_metrics(verdict)


def snapshot(path: str | None = None) -> dict:
    """The /v1/debug/perf payload: gate summary + per-entry trend rows.
    Loading also (re)publishes the gauges, so a scrape after the first
    debug read sees the same numbers."""
    verdict = load_verdict(path)
    if verdict is None:
        return {
            "verdict": None,
            "note": "no benchkeeper verdict recorded — run "
                    "`python -m tools.benchkeeper <BENCH_rNN.json>` "
                    "(or --smoke) to produce one",
            "verdictPath": path or verdict_path(),
        }
    try:
        publish_metrics(verdict)
    except Exception:  # metrics must never fail the debug surface
        pass
    trends = [{
        "id": r.get("id"),
        "section": r.get("section"),
        "metric": r.get("metric"),
        "kind": r.get("kind"),
        "unit": r.get("unit"),
        "status": r.get("status"),
        "baseline": r.get("baseline"),
        "value": r.get("value"),
        "deltaFrac": r.get("delta_frac"),
        "band": r.get("band"),
        "noise": r.get("noise") or {},
    } for r in verdict.get("entries", ())]
    return {
        "gate": {
            "ok": verdict.get("ok"),
            "refused": verdict.get("refused"),
            "checked": verdict.get("checked"),
            "passed": verdict.get("passed"),
            "regressions": verdict.get("regressions"),
            "stale": verdict.get("stale"),
            "missing": verdict.get("missing"),
            "generatedAt": verdict.get("generated_at"),
            "fingerprint": verdict.get("fingerprint"),
            "baselinePath": verdict.get("baseline_path"),
            "runs": verdict.get("runs"),
        },
        "trends": trends,
        "verdictPath": path or verdict_path(),
    }
