"""Tailboard: the always-on latency-attribution plane (ISSUE 15).

PR 2's tracing answers "where did THIS request spend its time" — but only
for the 1-in-N requests the sampler picked, and the requests an operator
actually needs (the slow ones, the errored ones, the degraded ones) are
exactly the ones most likely to miss the ring. Aggregate histograms
(`weaviate_tpu_query_duration_seconds`) answer "how slow overall" but not
"which phase". This module closes both gaps with four pieces that share
one design rule: NOTHING here may add a device synchronization to an
unsampled request (graftlint G1 stays empty for engine/) and nothing may
cost more than a contextvar read plus a few ``perf_counter`` stamps on
the hot path.

1. **Timeline** — a per-request phase accumulator opened at the REST and
   gRPC edges on EVERY data-path request. Layers that already hold
   monotonic stamps (the query batcher's enqueue/dispatch/transfer
   stamps) fold them in via :func:`phase`; the edge closes the timeline
   and the phases land in
   ``weaviate_tpu_request_phase_seconds{operation,phase,collection,
   tenant}`` with ``phase`` one of ``queue_wait | device | transfer |
   host``. "device" here is the dispatch→drain-start WALL window of the
   batch the request rode in — attribution without ``block_until_ready``
   (real ``device_ms`` stays sampled-only, in tracing). Tenant and
   collection labels pass a top-K guard (:class:`LabelGuard`) so an
   adversarial tenant stream cannot grow the exposition unboundedly.

2. **Tail-based retention** — the keep/drop decision for a finished
   trace moves to request COMPLETION: slow (per-operation threshold),
   errored (5xx), deadline-exceeded, degraded, and fault-injected
   requests are ALWAYS kept in a separate tail ring, regardless of
   ``TRACE_SAMPLE_RATE``, served at ``GET /v1/debug/traces?tail=true``.
   Phase-histogram buckets carry OpenMetrics exemplars naming a retained
   trace id, so a dashboard bucket links to an actual trace.

3. **SLO engine** — declarative per-operation availability + latency
   objectives (``WEAVIATE_TPU_SLO`` JSON, or defaults), sliding-window
   good/bad counters, multi-window burn-rate gauges
   (``weaviate_tpu_slo_burn_rate{slo,window}``), ``GET /v1/debug/slo``.
   A fast-window burn past threshold flips the PR 8 component-health
   registry (``slo:<name>`` component) and snapshots the flight
   recorder to disk.

4. **Flight recorder** — a lock-free ring of recent dispatch records
   (query batcher + native plane: batch size, k bucket, queue depth,
   wait, epoch fanout, transfer-window occupancy) plus the structured
   slow-query log (the PR 2 free-text slow-root log, made retrievable),
   served at ``GET /v1/debug/flight`` and written to
   ``<data_dir>/flightrecorder/`` on incident — so an r05-style
   post-hoc investigation has the dispatch history that produced the
   regression. "Lock-free" is literal: writers claim a slot with
   ``next(itertools.count())`` (one atomic C call under the GIL) and
   write it; a torn read under wrap-around drops one record instead of
   ever blocking a dispatch loop.

Env surface (all lazy-read, re-read after :func:`reset_for_tests`):

- ``WEAVIATE_TPU_TAILBOARD``        1 (default) / 0 — timeline on/off
- ``WEAVIATE_TPU_TAIL_SLOW_MS``     per-op slow threshold: a number, or
  JSON ``{"op-glob": ms, "*": ms}`` (default ``{"*": 250}``)
- ``WEAVIATE_TPU_TAIL_RING``        tail ring size (default 128)
- ``WEAVIATE_TPU_SLO``              JSON list of objectives
- ``WEAVIATE_TPU_SLO_WINDOWS``      csv seconds (default 60,300,3600)
- ``WEAVIATE_TPU_SLO_BURN_THRESHOLD`` incident burn rate (default 14.4,
  the classic fast-burn page threshold) evaluated on the shortest window
- ``WEAVIATE_TPU_FLIGHT_RING``      dispatch-record ring (default 256)
- ``WEAVIATE_TPU_TAILBOARD_MAX_TENANTS`` / ``_MAX_COLLECTIONS``
  top-K label guard (defaults 32 / 64)
"""

from __future__ import annotations

import contextvars
import fnmatch
import itertools
import json
import logging
import os
import threading
import time
from collections import deque

logger = logging.getLogger(__name__)

PHASES = ("queue_wait", "device", "transfer", "host")

#: tail-retention reasons, in decision priority order
TAIL_REASONS = ("deadline", "error", "degraded", "fault", "slow")


def _mono() -> float:
    return time.monotonic()


_faultline_mod = None


def _faultline():
    """Cached faultline module ref — the per-request finalize consults
    ``armed()`` and a repeated ``from ... import`` is measurable there."""
    global _faultline_mod
    if _faultline_mod is None:
        from weaviate_tpu.runtime import faultline

        _faultline_mod = faultline
    return _faultline_mod


# -- env policy (lazy, cached) ------------------------------------------------

_policy_lock = threading.Lock()
_enabled_cached: bool | None = None
_forced: bool | None = None  # force_enabled() override (bench/tests)
_slow_map: dict[str, float] | None = None  # op-glob -> seconds
_data_dir: str | None = None


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() in ("1", "true", "on", "enabled")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def enabled() -> bool:
    """Is the always-on timeline armed? (``WEAVIATE_TPU_TAILBOARD``,
    overridable by :func:`force_enabled` for the overhead bench)."""
    global _enabled_cached
    if _forced is not None:
        return _forced
    if _enabled_cached is None:
        _enabled_cached = _env_flag("WEAVIATE_TPU_TAILBOARD", True)
    return _enabled_cached


def force_enabled(value: bool | None) -> None:
    """Bench/test hook: pin the timeline on/off (None = back to env)."""
    global _forced
    _forced = value


def _slow_thresholds() -> dict[str, float]:
    """op-glob -> seconds; ``"*"`` is the fallback."""
    global _slow_map
    if _slow_map is None:
        raw = os.environ.get("WEAVIATE_TPU_TAIL_SLOW_MS", "").strip()
        out: dict[str, float] = {}
        if raw:
            try:
                parsed = json.loads(raw)
                if isinstance(parsed, dict):
                    out = {str(k): float(v) / 1000.0
                           for k, v in parsed.items()}
                else:
                    out = {"*": float(parsed) / 1000.0}
            except (ValueError, TypeError):
                logger.warning("WEAVIATE_TPU_TAIL_SLOW_MS=%r unparseable; "
                               "using the 250ms default", raw)
        out.setdefault("*", 0.25)
        _slow_map = out
    return _slow_map


_slow_cache: dict[str, float] = {}


def slow_threshold_s(operation: str) -> float:
    """Per-operation tail slow threshold in seconds (0 disables).
    Resolved once per operation (bounded set: route classes + rpc
    names) — this sits on the per-request finalize path."""
    hit = _slow_cache.get(operation)
    if hit is not None:
        return hit
    table = _slow_thresholds()
    if operation in table:
        out = table[operation]
    else:
        out = table["*"]
        for pat, v in table.items():
            if pat != "*" and fnmatch.fnmatchcase(operation, pat):
                out = v
                break
    if len(_slow_cache) < 1024:
        _slow_cache[operation] = out
    return out


def set_data_dir(path: str | None) -> None:
    """Where incident flight-recorder snapshots land
    (``<path>/flightrecorder/``). Wired by Database/Server construction."""
    global _data_dir
    _data_dir = path


def configure(data_dir: str | None = None, enabled: bool | None = None,
              slos_json: str | None = None) -> None:
    """Server-start wiring: one call applies the ServerConfig surface.
    A malformed SLO config logs and falls back to the defaults — same
    lenient contract as the lazy env read; observability config must
    never stop the server from booting."""
    if data_dir is not None:
        set_data_dir(data_dir)
    if enabled is not None:
        # explicit config wins over env in BOTH directions, like every
        # other ServerConfig field (from_env feeds the env value here
        # anyway, so env-driven deployments are unchanged)
        force_enabled(bool(enabled))
    if slos_json:
        try:
            slo_engine().configure_json(slos_json)
        except (ValueError, TypeError, KeyError) as e:
            logger.warning("WEAVIATE_TPU_SLO is unusable (%s); keeping "
                           "the default objectives", e)


# -- label-cardinality guard --------------------------------------------------


class LabelGuard:
    """Top-K distinct values for one label dimension; later arrivals
    collapse to the reserved ``other`` value so one adversarial stream
    of tenant/collection names cannot grow the exposition unboundedly.
    First-come-first-kept is deliberate: a steady production tenant set
    claims its slots at startup and keeps them."""

    __slots__ = ("cap", "_seen", "_lock")

    def __init__(self, cap: int):
        self.cap = max(1, int(cap))
        self._seen: set[str] = set()
        self._lock = threading.Lock()

    def clamp(self, value: str | None) -> str:
        if not value:
            return "-"
        value = str(value)
        if value in self._seen:  # benign race: set lookups are GIL-atomic
            return value
        with self._lock:
            if value in self._seen:
                return value
            if len(self._seen) < self.cap:
                self._seen.add(value)
                return value
        return "other"


_tenant_guard: LabelGuard | None = None
_collection_guard: LabelGuard | None = None

# (operation, phase, collection, tenant) -> histogram child. labels()
# takes the metric lock and rebuilds the key tuple on every call; this
# cache turns the per-request finalize into plain dict hits. Bounded:
# keys only form from guard-clamped values x the closed phase set.
_phase_child_cache: dict[tuple, object] = {}


def _phase_child(operation: str, phase_name: str, collection: str,
                 tenant: str):
    key = (operation, phase_name, collection, tenant)
    child = _phase_child_cache.get(key)
    if child is None:
        from weaviate_tpu.runtime.metrics import request_phase_seconds

        child = request_phase_seconds.labels(*key)
        if len(_phase_child_cache) < 8192:
            _phase_child_cache[key] = child
    return child


def _guards() -> tuple[LabelGuard, LabelGuard]:
    global _tenant_guard, _collection_guard
    if _tenant_guard is None:
        _tenant_guard = LabelGuard(
            _env_int("WEAVIATE_TPU_TAILBOARD_MAX_TENANTS", 32))
        _collection_guard = LabelGuard(
            _env_int("WEAVIATE_TPU_TAILBOARD_MAX_COLLECTIONS", 64))
    return _tenant_guard, _collection_guard


# -- the per-request timeline -------------------------------------------------


class Timeline:
    """Phase accumulator for one request. Mutated from the request
    thread only (the batcher folds its worker-side stamps in AFTER its
    waiter wakes, on the request thread), so no lock."""

    __slots__ = ("operation", "method", "collection", "tenant", "status",
                 "degraded", "fault", "phases", "trace", "_t0")

    def __init__(self, operation: str, method: str = ""):
        self.operation = operation
        self.method = method
        self.collection: str | None = None
        self.tenant: str | None = None
        self.status: int | None = None
        self.degraded = False
        self.fault = False
        self.phases: dict[str, float] = {}
        self.trace: dict | None = None  # attached by on_trace_complete
        self._t0 = time.perf_counter()

    def add_phase(self, name: str, seconds: float) -> None:
        if seconds > 0.0:
            self.phases[name] = self.phases.get(name, 0.0) + seconds


_timeline: contextvars.ContextVar[Timeline | None] = contextvars.ContextVar(
    "weaviate_tpu_timeline", default=None)


class _NullTimelineCM:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_TIMELINE_CM = _NullTimelineCM()


class _TimelineCM:
    __slots__ = ("_tl", "_token")

    def __init__(self, operation: str, method: str):
        self._tl = Timeline(operation, method)

    def __enter__(self):
        self._token = _timeline.set(self._tl)
        return self._tl

    def __exit__(self, exc_type, exc, tb):
        _timeline.reset(self._token)
        try:
            _finish_timeline(self._tl, exc)
        except Exception:  # observability must never fail a request
            logger.exception("tailboard timeline finalize failed")
        return False


def request(operation: str, method: str = ""):
    """Edge entry point: open the always-on timeline for one request.
    Cheap no-op when the tailboard is disabled."""
    if not enabled():
        return _NULL_TIMELINE_CM
    return _TimelineCM(operation, method)


def current() -> Timeline | None:
    return _timeline.get()


def phase(name: str, seconds: float) -> None:
    """Fold an externally-timed phase into the live timeline (no-op
    outside one). Called from layers that already hold the stamps —
    never adds a sync of its own."""
    tl = _timeline.get()
    if tl is not None:
        tl.add_phase(name, seconds)


def annotate(collection: str | None = None, tenant: str | None = None) -> None:
    """Attach collection/tenant identity to the live timeline (no-op
    outside one)."""
    tl = _timeline.get()
    if tl is None:
        return
    if collection:
        tl.collection = str(collection)
    if tenant:
        tl.tenant = str(tenant)


def complete(status: int, degraded: bool = False) -> None:
    """Edge exit point: record the response status before the timeline
    closes (the tail keep/drop decision and the SLO verdict need it)."""
    tl = _timeline.get()
    if tl is not None:
        tl.status = int(status)
        if degraded:
            tl.degraded = True


def note_fault() -> None:
    """Mark the live timeline fault-injected (called by faultline on the
    request thread; worker-thread injections are found by the armed-scan
    in the keep decision instead)."""
    tl = _timeline.get()
    if tl is not None:
        tl.fault = True


# -- tail ring ----------------------------------------------------------------

_tail_lock = threading.Lock()
_tail_ring: deque | None = None


def _tail() -> deque:
    global _tail_ring
    if _tail_ring is None:
        _tail_ring = deque(maxlen=_env_int("WEAVIATE_TPU_TAIL_RING", 128))
    return _tail_ring


def tail_traces(limit: int = 50) -> list[dict]:
    """Newest-first tail-retained entries for
    ``GET /v1/debug/traces?tail=true``."""
    with _tail_lock:
        items = list(_tail())
    return items[::-1][: max(0, limit)]


def clear_tail() -> None:
    """Drop the tail ring (tests; the tracing.clear_traces analog)."""
    with _tail_lock:
        _tail().clear()


def _keep_tail(entry: dict) -> None:
    with _tail_lock:
        _tail().append(entry)
    try:
        from weaviate_tpu.runtime.metrics import tail_retained_total

        tail_retained_total.labels(entry["reason"]).inc()
    except Exception:  # pragma: no cover
        pass


def _trace_has_fault(trace_dict: dict | None) -> bool:
    """Scan a finished trace for faultline annotations. Only called when
    a schedule is armed (chaos runs), never on the clean hot path."""
    if not trace_dict:
        return False
    for sp in trace_dict.get("spans", ()):
        if "fault_point" in (sp.get("attrs") or ()):
            return True
    return False


def _tail_reason(tl: Timeline, duration_s: float,
                 exc: BaseException | None) -> str | None:
    status = tl.status
    # fast path: a clean, fast 2xx/3xx/4xx with nothing flagged — the
    # overwhelming majority of requests — answers with two compares and
    # one cached threshold lookup
    if (exc is None and status is not None and status != 504
            and status < 500 and not tl.degraded and not tl.fault
            and duration_s < slow_threshold_s(tl.operation)
            and not _faultline().armed()):
        return None
    if status == 504:
        return "deadline"
    # a SET status wins over a propagating exception: the gRPC edge
    # calls complete(4xx) and then context.abort(), whose control-flow
    # exception unwinds through the timeline CM — a handled client
    # error must not count as a server error
    if (status >= 500) if status is not None else (exc is not None):
        return "error"
    if tl.degraded:
        return "degraded"
    if tl.fault:
        return "fault"
    threshold = slow_threshold_s(tl.operation)
    if 0 < threshold <= duration_s:
        return "slow"
    try:  # armed-only span scan (worker-thread injections)
        fl = _faultline()
        if fl.armed() and _trace_has_fault(tl.trace):
            return "fault"
    except Exception:  # pragma: no cover
        pass
    return None


# -- deferred fold ------------------------------------------------------------
#
# The request thread must pay for STAMPS, not aggregation: finishing a
# timeline pushes one small record into a lock-free ring and returns.
# Folding those records into the phase histograms and the SLO windows
# happens amortized (every _FOLD_EVERY-th request folds the backlog
# inline, ~30us per 512 requests) and at every read point (metrics
# scrape, /v1/debug/slo, /v1/debug/flight call flush()), so readers
# always see current state. Each record carries its own SLO bucket
# stamp — deferral shifts WHEN the math runs, never which window an
# observation lands in.

_FOLD_EVERY = 512
_PENDING_SIZE = 4096

_fold_lock = threading.Lock()
_pending_buf: list = [None] * _PENDING_SIZE
_pending_seq = itertools.count(1)
_pending_folded = 0  # last folded seq (guarded by _fold_lock)


def _finish_timeline(tl: Timeline, exc: BaseException | None) -> None:
    duration = time.perf_counter() - tl._t0
    reason = _tail_reason(tl, duration, exc)
    trace_id = (tl.trace or {}).get("trace_id")
    if reason is not None:  # rare path: keep the full trace NOW
        attributed = sum(tl.phases.values())
        phases_ms = {p: round(v * 1000.0, 3)
                     for p, v in tl.phases.items()}
        phases_ms["host"] = round(
            max(duration - attributed, 0.0) * 1000.0, 3)
        _keep_tail({
            "reason": reason,
            "operation": tl.operation,
            "method": tl.method,
            "status": tl.status,
            "collection": tl.collection,
            "tenant": tl.tenant,
            "duration_ms": round(duration * 1000.0, 3),
            "phases_ms": phases_ms,
            "kept_at": time.time(),
            "trace": tl.trace,
        })
    # record tuple: (seq, operation, phases, duration_s, errored,
    # collection, tenant, trace_id, bucket) — a tuple, not a dict: this
    # build runs on every request's thread
    # same status-wins rule as _tail_reason (abort control flow is not
    # an availability failure when the edge already mapped a 4xx)
    errored = ((tl.status >= 500) if tl.status is not None
               else (exc is not None))
    seq = next(_pending_seq)
    _pending_buf[seq % _PENDING_SIZE] = (
        seq, tl.operation, tl.phases, duration, errored,
        tl.collection, tl.tenant,
        trace_id if reason is not None else None,
        int(_mono() // _BUCKET_S),
    )
    if seq % _FOLD_EVERY == 0:
        flush()


def flush() -> None:
    """Fold every pending completion record into the phase histograms
    and the SLO windows. Called by read points and the amortized inline
    trigger; idempotent and cheap when there is no backlog. SLO window
    increments batch per (objective, bucket) so a 512-record fold takes
    a handful of lock acquisitions, not thousands.

    Lock-free loss bound: a writer preempted between claiming its seq
    and storing the record can have that ONE record skipped (a fold
    that ran in between advances past its seq) — the same
    drop-one-rather-than-block tradeoff as :class:`FlightRing`, and it
    costs one phase/SLO observation, never a tail-ring entry (those are
    kept synchronously at completion)."""
    global _pending_folded
    with _fold_lock:
        found = [r for r in list(_pending_buf)
                 if r is not None and r[0] > _pending_folded]
        if not found:
            return
        found.sort()
        _pending_folded = found[-1][0]
        eng = slo_engine()
        horizon = eng.horizon_buckets()
        tenant_guard, coll_guard = _guards()
        slo_acc: dict[tuple, list[float]] = {}  # (obj, bucket) -> [g, b]
        for (_seq, operation, phases, duration_s, errored, collection,
             tenant, trace_id, bucket) in found:
            host = duration_s - sum(phases.values())
            collection = coll_guard.clamp(collection)
            tenant = tenant_guard.clamp(tenant)
            # exemplars only for tail-retained traces, so a bucket's
            # exemplar always RESOLVES through /v1/debug/traces?tail=true
            exemplar = {"trace_id": trace_id} if trace_id else None
            try:
                for p, v in phases.items():
                    _phase_child(operation, p, collection,
                                 tenant).observe(v, exemplar=exemplar)
                _phase_child(operation, "host", collection,
                             tenant).observe(max(host, 0.0),
                                             exemplar=exemplar)
            except Exception:  # pragma: no cover — never fail a reader
                pass
            for o in eng.objectives_for(operation):
                verdict = o.verdict(500 if errored else 200,
                                    duration_s, None)
                if verdict is not None:
                    cell = slo_acc.setdefault((o, bucket), [0.0, 0.0])
                    cell[0 if verdict else 1] += 1.0
        for (o, bucket), (good, bad) in slo_acc.items():
            o.record_bulk(bucket, good, bad, horizon)
    eng.maybe_sweep()


def on_trace_complete(trace_dict: dict, root_name: str,
                      duration_ms: float) -> None:
    """tracing._finalize hook, called for EVERY finished root trace.

    Inside a timeline (edge requests): just attach the trace — the
    timeline exit, which also knows the response status, makes the
    keep/drop decision. Outside one (direct ``tracing.trace`` users,
    worker roots): a standalone slow/fault decision so those traces can
    still be tail-kept."""
    tl = _timeline.get()
    if tl is not None:
        tl.trace = trace_dict
        return
    if not enabled():
        return
    reason = None
    duration_s = duration_ms / 1000.0
    threshold = slow_threshold_s(root_name)
    if 0 < threshold <= duration_s:
        reason = "slow"
    else:
        try:
            if _faultline().armed() and _trace_has_fault(trace_dict):
                reason = "fault"
        except Exception:  # pragma: no cover
            pass
    if reason is not None:
        _keep_tail({
            "reason": reason, "operation": root_name, "method": "",
            "status": None, "collection": None, "tenant": None,
            "duration_ms": round(duration_ms, 3),
            "phases_ms": {}, "kept_at": time.time(),
            "trace": trace_dict,
        })


# -- SLO engine ---------------------------------------------------------------

_BUCKET_S = 5.0  # sliding-window granularity

_DEFAULT_SLOS = (
    {"slo": "availability", "operation": "*", "kind": "availability",
     "objective": 0.999},
    {"slo": "latency", "operation": "*", "kind": "latency",
     "objective": 0.99, "threshold_ms": 500.0},
)


class _Objective:
    __slots__ = ("name", "operation", "kind", "objective", "threshold_s",
                 "counts", "lock")

    def __init__(self, spec: dict):
        self.name = str(spec["slo"])
        self.operation = str(spec.get("operation", "*"))
        self.kind = str(spec.get("kind", "availability"))
        if self.kind not in ("availability", "latency"):
            raise ValueError(f"SLO {self.name!r}: unknown kind "
                             f"{self.kind!r}")
        self.objective = float(spec.get("objective", 0.999))
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"SLO {self.name!r}: objective must be in "
                             f"(0, 1), got {self.objective}")
        self.threshold_s = float(spec.get("threshold_ms", 500.0)) / 1000.0
        # bucket index -> [good, bad]; pruned past the longest window
        self.counts: dict[int, list[float]] = {}
        self.lock = threading.Lock()

    def matches(self, operation: str) -> bool:
        return fnmatch.fnmatchcase(operation, self.operation)

    def verdict(self, status: int | None, duration_s: float,
                exc: BaseException | None) -> bool | None:
        """True = good, False = bad, None = excluded from this SLO."""
        errored = exc is not None or (status is not None and status >= 500)
        if self.kind == "availability":
            return not errored
        if errored:  # latency SLOs judge only requests that succeeded
            return None
        return duration_s <= self.threshold_s

    def record(self, bucket: int, good: bool, horizon: int) -> None:
        self.record_bulk(bucket, 1.0 if good else 0.0,
                         0.0 if good else 1.0, horizon)

    def record_bulk(self, bucket: int, good: float, bad: float,
                    horizon: int) -> None:
        with self.lock:
            cell = self.counts.get(bucket)
            if cell is None:
                cell = self.counts[bucket] = [0.0, 0.0]
                # prune on new-bucket creation: O(1) amortized
                dead = [b for b in self.counts if b < bucket - horizon]
                for b in dead:
                    del self.counts[b]
            cell[0] += good
            cell[1] += bad

    def window_counts(self, now_bucket: int, window_s: float) -> tuple:
        lo = now_bucket - int(window_s // _BUCKET_S)
        good = bad = 0.0
        with self.lock:
            for b, (g, x) in self.counts.items():
                if lo < b <= now_bucket:
                    good += g
                    bad += x
        return good, bad

    def burn_rate(self, now_bucket: int, window_s: float) -> float:
        """bad-fraction over the window divided by the error budget
        (1 - objective): 1.0 = burning exactly the budget, >>1 = paging
        territory. 0 when the window saw no traffic."""
        good, bad = self.window_counts(now_bucket, window_s)
        total = good + bad
        if total <= 0:
            return 0.0
        return (bad / total) / (1.0 - self.objective)


class SloEngine:
    """All objectives + the incident loop. One process-wide instance."""

    def __init__(self):
        self._lock = threading.Lock()
        self._objectives: list[_Objective] | None = None
        self._windows: tuple[float, ...] | None = None
        self._match_cache: dict[str, tuple[_Objective, ...]] = {}
        self._last_check = 0.0
        self._burning: set[str] = set()

    # -- configuration --------------------------------------------------------

    def _load(self) -> list[_Objective]:
        with self._lock:
            if self._objectives is None:
                raw = os.environ.get("WEAVIATE_TPU_SLO", "").strip()
                specs = _DEFAULT_SLOS
                if raw:
                    try:
                        parsed = json.loads(raw)
                        if isinstance(parsed, list) and parsed:
                            specs = parsed
                        else:
                            logger.warning("WEAVIATE_TPU_SLO must be a "
                                           "non-empty JSON list; using "
                                           "defaults")
                    except ValueError:
                        logger.warning("WEAVIATE_TPU_SLO is not valid "
                                       "JSON; using defaults")
                self._objectives = [_Objective(dict(s)) for s in specs]
                self._match_cache.clear()
            return self._objectives

    def configure_json(self, raw: str) -> None:
        """Explicit (re)configuration — ServerConfig wiring and tests."""
        specs = json.loads(raw)
        with self._lock:
            self._objectives = [_Objective(dict(s)) for s in specs]
            self._match_cache.clear()
            self._burning.clear()

    def windows(self) -> tuple[float, ...]:
        with self._lock:
            if self._windows is None:
                raw = os.environ.get("WEAVIATE_TPU_SLO_WINDOWS",
                                     "60,300,3600")
                try:
                    ws = tuple(sorted(float(w) for w in raw.split(",")
                                      if w.strip()))
                except ValueError:
                    ws = (60.0, 300.0, 3600.0)
                self._windows = ws or (60.0, 300.0, 3600.0)
            return self._windows

    def burn_threshold(self) -> float:
        return _env_float("WEAVIATE_TPU_SLO_BURN_THRESHOLD", 14.4)

    def horizon_buckets(self) -> int:
        return int(max(self.windows()) // _BUCKET_S) + 1

    def objectives_for(self, operation: str) -> tuple[_Objective, ...]:
        hit = self._match_cache.get(operation)
        if hit is None:
            objs = self._load()
            hit = tuple(o for o in objs if o.matches(operation))
            # the op set is bounded (route classes + rpc names), so the
            # cache is too
            if len(self._match_cache) < 256:
                self._match_cache[operation] = hit
        return hit

    def maybe_sweep(self) -> None:
        """Rate-limited incident sweep — at most once a second, however
        often the fold runs."""
        now = _mono()
        with self._lock:
            due = now - self._last_check >= 1.0
            if due:
                self._last_check = now
        if due:
            try:
                self.check_incidents(now=now)
            except Exception:  # pragma: no cover
                logger.exception("SLO incident sweep failed")

    # -- evaluation -----------------------------------------------------------

    def check_incidents(self, now: float | None = None) -> None:
        """Fast-window burn over threshold => flip the component-health
        registry (``slo:<name>``) and snapshot the flight recorder;
        recovery flips it back."""
        from weaviate_tpu.runtime import degrade

        now = _mono() if now is None else now
        bucket = int(now // _BUCKET_S)
        fast = self.windows()[0]
        threshold = self.burn_threshold()
        for o in self._load():
            burn = o.burn_rate(bucket, fast)
            component = f"slo:{o.name}"
            if burn >= threshold:
                if o.name not in self._burning:
                    self._burning.add(o.name)
                    reason = (f"burn rate {burn:.1f}x over the "
                              f"{int(fast)}s window (threshold "
                              f"{threshold:.1f}x, objective "
                              f"{o.objective})")
                    degrade.mark_unhealthy(component, reason)
                    snapshot_to_disk(f"slo:{o.name}")
            elif o.name in self._burning:
                self._burning.discard(o.name)
                degrade.mark_healthy(component)

    def refresh(self, now: float | None = None) -> None:
        """Republish the burn-rate gauges + run the incident sweep —
        called at scrape time and from /v1/debug/slo, like
        perfgate.refresh."""
        now = _mono() if now is None else now
        bucket = int(now // _BUCKET_S)
        try:
            from weaviate_tpu.runtime.metrics import slo_burn_rate

            for o in self._load():
                for w in self.windows():
                    slo_burn_rate.labels(o.name, f"{int(w)}s").set(
                        o.burn_rate(bucket, w))
        except Exception:  # pragma: no cover
            pass
        self.check_incidents(now=now)

    def snapshot(self, now: float | None = None) -> dict:
        """The /v1/debug/slo payload."""
        now = _mono() if now is None else now
        bucket = int(now // _BUCKET_S)
        out = []
        for o in self._load():
            windows = {}
            for w in self.windows():
                good, bad = o.window_counts(bucket, w)
                windows[f"{int(w)}s"] = {
                    "good": good, "bad": bad,
                    "burnRate": round(o.burn_rate(bucket, w), 4),
                }
            spec = {
                "slo": o.name, "operation": o.operation, "kind": o.kind,
                "objective": o.objective, "windows": windows,
                "burning": o.name in self._burning,
            }
            if o.kind == "latency":
                spec["thresholdMs"] = round(o.threshold_s * 1000.0, 3)
            out.append(spec)
        return {"slos": out,
                "burnThreshold": self.burn_threshold(),
                "fastWindowSeconds": self.windows()[0]}


_slo_engine: SloEngine | None = None
_slo_lock = threading.Lock()


def slo_engine() -> SloEngine:
    global _slo_engine
    if _slo_engine is None:
        with _slo_lock:
            if _slo_engine is None:
                _slo_engine = SloEngine()
    return _slo_engine


# -- flight recorder ----------------------------------------------------------


class FlightRing:
    """Fixed-size lock-free ring. Writers claim a slot via
    ``next(itertools.count())`` (atomic under the GIL) and store; readers
    copy the buffer. Under wrap-around a reader can see a record from
    either generation for a given slot — acceptable for a flight
    recorder, and the price of never blocking a dispatch loop."""

    __slots__ = ("_size", "_buf", "_seq")

    def __init__(self, size: int):
        self._size = max(8, int(size))
        self._buf: list[dict | None] = [None] * self._size
        self._seq = itertools.count()

    def append(self, record: dict) -> None:
        i = next(self._seq)
        record["seq"] = i
        self._buf[i % self._size] = record

    def snapshot(self) -> list[dict]:
        """Oldest-first records (sorted by claim sequence)."""
        items = [r for r in list(self._buf) if r is not None]
        items.sort(key=lambda r: r.get("seq", 0))
        return items


_flight_ring: FlightRing | None = None
_slowlog_ring: FlightRing | None = None


def _flight() -> FlightRing:
    global _flight_ring
    if _flight_ring is None:
        _flight_ring = FlightRing(_env_int("WEAVIATE_TPU_FLIGHT_RING", 256))
    return _flight_ring


def _slowlog() -> FlightRing:
    global _slowlog_ring
    if _slowlog_ring is None:
        _slowlog_ring = FlightRing(64)
    return _slowlog_ring


def record_dispatch(plane: str, **fields) -> dict:
    """One dispatch record from the query batcher or the native plane.
    Lock-free, allocation-light — safe on the dispatch hot loop. Returns
    the live record so a caller may patch in late-arriving fields (the
    batcher learns its epoch fanout only after the async launch)."""
    rec = {"plane": plane, "t": time.time()}
    rec.update(fields)
    _flight().append(rec)
    return rec


def slow_root(record: dict) -> None:
    """Structured slow-query entry (tracing's slow-root path lands here
    instead of free-text-only logging)."""
    _slowlog().append(dict(record))


def debug_flight() -> dict:
    """The /v1/debug/flight payload."""
    flush()
    return {
        "dispatches": _flight().snapshot(),
        "slowlog": _slowlog().snapshot(),
        "snapshots": _snapshot_files(),
    }


# -- incident snapshots -------------------------------------------------------

_SNAPSHOT_KEEP = 8
_snapshot_lock = threading.Lock()
_last_snapshot: float | None = None


def _snapshot_dir() -> str | None:
    return os.path.join(_data_dir, "flightrecorder") if _data_dir else None


def _snapshot_files() -> list[str]:
    d = _snapshot_dir()
    if not d or not os.path.isdir(d):
        return []
    try:
        return sorted(f for f in os.listdir(d) if f.endswith(".json"))
    except OSError:
        return []


def snapshot_cooldown_s() -> float:
    return _env_float("WEAVIATE_TPU_FLIGHT_SNAPSHOT_COOLDOWN_S", 30.0)


def snapshot_to_disk(reason: str, force: bool = False) -> str | None:
    """Persist the flight recorder + SLO state on incident (SLO burn,
    component-health flip). Cooldown-limited so a flapping incident
    cannot spam the data dir; keeps the newest ``_SNAPSHOT_KEEP`` files.
    Returns the written path, or None (no data dir / cooldown)."""
    global _last_snapshot
    d = _snapshot_dir()
    if d is None:
        return None
    now = _mono()
    with _snapshot_lock:
        if (not force and _last_snapshot is not None
                and now - _last_snapshot < snapshot_cooldown_s()):
            return None
        _last_snapshot = now
    try:
        from weaviate_tpu.runtime import degrade

        payload = {
            "written_at": time.time(),
            "reason": reason,
            "dispatches": _flight().snapshot(),
            "slowlog": _slowlog().snapshot(),
            "slo": slo_engine().snapshot(),
            "componentHealth": degrade.health(),
            "tail": tail_traces(16),
        }
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"flight-{int(time.time() * 1000)}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        files = _snapshot_files()
        for stale in files[:-_SNAPSHOT_KEEP]:
            try:
                os.unlink(os.path.join(d, stale))
            except OSError:
                pass
        try:
            from weaviate_tpu.runtime.metrics import flight_snapshots_total

            flight_snapshots_total.labels(reason).inc()
        except Exception:  # pragma: no cover
            pass
        logger.warning("flight-recorder snapshot written: %s (%s)",
                       path, reason)
        return path
    except Exception:  # incident capture must never crash serving
        logger.exception("flight-recorder snapshot failed")
        return None


def on_component_unhealthy(component: str, reason: str) -> None:
    """degrade.mark_unhealthy hook: a component flipping unhealthy is an
    incident — capture the dispatch history that led to it. SLO flips
    come through here too (mark_unhealthy call order), deduped by the
    snapshot cooldown."""
    if component.startswith("slo:"):
        return  # check_incidents already snapshotted with the burn reason
    snapshot_to_disk(f"component:{component}")


# -- debug payloads -----------------------------------------------------------


def debug_slo() -> dict:
    flush()
    eng = slo_engine()
    eng.refresh()
    return eng.snapshot()


def scrape_refresh() -> None:
    """Read-point hook for the /v1/metrics scrape paths: fold the
    pending completion records, then republish the burn gauges (and run
    the incident sweep)."""
    flush()
    slo_engine().refresh()


# -- test isolation -----------------------------------------------------------


def reset_for_tests() -> None:
    """Drop every cached policy/registry so the next use re-reads env —
    the conftest autouse fixture calls this between tests."""
    global _enabled_cached, _forced, _slow_map, _data_dir
    global _tail_ring, _flight_ring, _slowlog_ring, _slo_engine
    global _tenant_guard, _collection_guard, _last_snapshot
    global _pending_seq, _pending_folded
    _enabled_cached = None
    _forced = None
    _slow_map = None
    _slow_cache.clear()
    _data_dir = None
    _tail_ring = None
    _flight_ring = None
    _slowlog_ring = None
    _slo_engine = None
    _tenant_guard = None
    _collection_guard = None
    _phase_child_cache.clear()
    with _fold_lock:
        for i in range(len(_pending_buf)):
            _pending_buf[i] = None
        _pending_seq = itertools.count(1)
        _pending_folded = 0
    with _snapshot_lock:
        _last_snapshot = None
