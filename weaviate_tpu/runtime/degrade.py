"""Degraded-mode bookkeeping: partial-result markers + component health.

Two small registries shared by the serving layers:

- **Per-request degradation markers.** The REST/gRPC edge opens
  ``collecting()`` around each request; any layer that serves PARTIAL
  results instead of failing (a dead replica skipped by scatter-gather,
  a consistency level quietly downgraded by the finder) calls
  ``report(...)``. The edge attaches the collected markers to the
  response as an explicit ``degraded`` field — a client must never
  mistake a partial answer for a complete one. Markers ride a
  contextvar (carried onto pool threads by ``tracing.propagate``).

- **Component health.** Long-lived subsystems (query batcher, native
  data plane) flip ``mark_unhealthy``/``mark_healthy`` as their
  dispatch paths fail and recover; ``/v1/nodes`` surfaces the registry
  so an operator sees WHICH component is degraded, not just that p99
  went sideways. Mirrored in the
  ``weaviate_tpu_component_unhealthy{component}`` gauge.
"""

from __future__ import annotations

import contextvars
import threading
import time

_markers_var: contextvars.ContextVar[list | None] = contextvars.ContextVar(
    "weaviate_tpu_degraded_markers", default=None)


class _Collecting:
    __slots__ = ("_token",)

    def __enter__(self):
        self._token = _markers_var.set([])
        return self

    def __exit__(self, *exc):
        _markers_var.reset(self._token)
        return False


def collecting() -> _Collecting:
    """Request-edge scope: markers reported inside land in ``snapshot()``."""
    return _Collecting()


def current_markers() -> list | None:
    """The live marker list (for hand-off to pool threads), or None."""
    return _markers_var.get()


def set_markers(markers: list | None):
    """Install a captured marker list on a worker thread; returns the
    reset token (``tracing.propagate`` plumbing)."""
    return _markers_var.set(markers)


def reset_markers(token) -> None:
    _markers_var.reset(token)


def report(kind: str, *, collection: str = "", shard: str = "",
           node: str = "", detail: str = "") -> None:
    """Record one degradation: the request edge surfaces it, the counter
    accounts for it even when no edge is collecting (direct API use)."""
    marker = {"kind": kind}
    if collection:
        marker["collection"] = collection
    if shard:
        marker["shard"] = shard
    if node:
        marker["node"] = node
    if detail:
        marker["detail"] = detail
    markers = _markers_var.get()
    if markers is not None:
        markers.append(marker)
    try:
        from weaviate_tpu.runtime.metrics import degraded_results_total

        degraded_results_total.labels(kind, collection or "-").inc()
    except Exception:  # pragma: no cover
        pass
    try:
        from weaviate_tpu.runtime import tracing

        tracing.annotate(degraded=kind)
    except Exception:  # pragma: no cover
        pass


def snapshot() -> list[dict]:
    """The markers collected so far in this request (empty when none, or
    when no edge is collecting)."""
    markers = _markers_var.get()
    return list(markers) if markers else []


# -- component health ----------------------------------------------------------

_health_lock = threading.Lock()
_unhealthy: dict[str, dict] = {}


def mark_unhealthy(component: str, reason: str) -> None:
    """Flip a component's health flag (idempotent; first reason+time
    stick until it recovers)."""
    flipped = False
    with _health_lock:
        if component not in _unhealthy:
            _unhealthy[component] = {"reason": reason,
                                     "since": time.time()}
            _set_gauge(component, 1.0)
            flipped = True
        else:
            _unhealthy[component]["reason"] = reason
    if flipped:
        # a component FLIPPING unhealthy is an incident: capture the
        # dispatch history that led here (flight-recorder snapshot,
        # cooldown-limited) — outside the health lock on purpose
        try:
            from weaviate_tpu.runtime import tailboard

            tailboard.on_component_unhealthy(component, reason)
        except Exception:  # pragma: no cover — never fail the caller
            pass


def mark_healthy(component: str) -> None:
    with _health_lock:
        if _unhealthy.pop(component, None) is not None:
            _set_gauge(component, None)


def is_unhealthy(component: str) -> bool:
    """Lock-free membership probe (benign race): lets hot paths skip
    the mark_healthy lock when the component was never flagged."""
    return component in _unhealthy


def health() -> dict:
    """``{"healthy": bool, "unhealthy": {component: {reason, since}}}`` —
    the ``/v1/nodes`` payload."""
    with _health_lock:
        bad = {k: dict(v) for k, v in _unhealthy.items()}
    return {"healthy": not bad, "unhealthy": bad}


def _set_gauge(component: str, value: float | None) -> None:
    try:
        from weaviate_tpu.runtime.metrics import component_unhealthy

        if value is None:
            component_unhealthy.remove(component)
        else:
            component_unhealthy.labels(component).set(value)
    except Exception:  # pragma: no cover
        pass


def reset() -> None:
    """Test hook: clear the health registry."""
    with _health_lock:
        for component in list(_unhealthy):
            _unhealthy.pop(component)
            _set_gauge(component, None)
