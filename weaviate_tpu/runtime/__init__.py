"""Background-task runtime: cycle scheduler, memory watchdog, metrics.

Reference: entities/cyclemanager/ (CycleManager, exponential tickers),
usecases/memwatch/ (allocation gate), usecases/monitoring/ (prometheus
registry).
"""

from weaviate_tpu.runtime.cyclemanager import CycleCallback, CycleManager
from weaviate_tpu.runtime.hbm_ledger import HBMLedger, ledger
from weaviate_tpu.runtime.memwatch import MemoryMonitor
from weaviate_tpu.runtime.metrics import Counter, Gauge, Histogram, MetricsRegistry, registry

__all__ = [
    "CycleCallback",
    "CycleManager",
    "HBMLedger",
    "ledger",
    "MemoryMonitor",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
]
