"""HBM ledger: host-side accounting of labeled device allocations.

The allocator's own stats (``jax device.memory_stats()``) answer "how
full is the device" but return ``{}`` on CPU meshes and remote-tunnel
TPUs — and even where they exist they cannot answer "WHICH collection/
shard/tenant owns my HBM". The reference's memwatch (usecases/memwatch/
monitor.go CheckAlloc) refuses imports *before* allocating; Milvus-style
quota/segment accounting keeps a host-side ledger per segment. This
module is both: every device-resident allocation registers a labeled
entry ``(collection, shard, tenant, component, dtype, nbytes,
sharding)`` and the running totals drive

- Prometheus gauges (``hbm_bytes{collection,shard,component}``,
  ``hbm_peak_bytes``, ``hbm_budget_bytes`` — runtime/metrics.py),
- ``GET /v1/debug/memory`` (api/rest.py breakdown endpoint), and
- capacity-aware admission: ``MemoryMonitor.check_device_alloc`` falls
  back to ledger-projected totals when allocator stats are unavailable
  (runtime/memwatch.py watermark gating).

Ownership labels travel via a contextvar (``owner()``): the shard layer
sets the (collection, shard, tenant) scope around index construction and
the engine-level stores capture it once — deep allocation code never
needs label plumbing through its signatures. Long-lived buffers hold a
key and ``update()`` it across grows; transient buffers either
``release()`` explicitly or ride ``track()``, which ties the entry's
lifetime to the device array itself via weakref.

The ledger tracks LOGICAL bytes (``arr.nbytes``): on a row-sharded mesh
that is the global footprint summed over devices, the number a capacity
planner wants. Replicated operands count once per logical array, so the
allocator-vs-ledger delta (surfaced by /v1/debug/memory when allocator
stats exist) includes replication overhead, executables beyond the
estimate, and XLA scratch.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import weakref
from dataclasses import dataclass

_UNOWNED = {"collection": "_unowned", "shard": "-", "tenant": ""}

_owner_ctx: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "hbm_owner", default=None)


@contextlib.contextmanager
def owner(collection: str, shard: str = "-", tenant: str = ""):
    """Scope: allocations registered inside run under these labels."""
    token = _owner_ctx.set({"collection": str(collection),
                            "shard": str(shard), "tenant": str(tenant)})
    try:
        yield
    finally:
        _owner_ctx.reset(token)


def current_owner() -> dict:
    """The ambient (collection, shard, tenant) labels, or the _unowned
    placeholder for allocations made outside any shard scope (tests,
    benches, module-level singletons)."""
    return dict(_owner_ctx.get() or _UNOWNED)


@dataclass
class Entry:
    key: int
    collection: str
    shard: str
    tenant: str
    component: str
    dtype: str
    nbytes: int
    sharding: str  # "single" | "sharded" | "replicated" | "estimate"
    placement: str  # "device" | "host"


class HBMLedger:
    """Thread-safe allocation registry with running totals + peaks."""

    def __init__(self):
        # RLock: weakref.finalize callbacks (track()) release entries and
        # can fire from cyclic GC triggered by an allocation INSIDE a
        # locked section on the same thread — a plain Lock would
        # self-deadlock there
        self._lock = threading.RLock()
        self._entries: dict[int, Entry] = {}
        self._next_key = 1
        self._device_total = 0
        self._device_peak = 0
        # incremental rollups so the admission path never iterates entries
        self._by_collection: dict[str, int] = {}
        self._by_shard: dict[tuple[str, str], int] = {}
        self._by_gauge: dict[tuple[str, str, str], int] = {}
        # mesh host count hint (set once at startup when the mesh is
        # known) so scrape-time host-gauge refreshes need no mesh access
        self._host_count_hint = 1

    # -- registration ---------------------------------------------------------

    def register(self, component: str, nbytes: int, *,
                 collection: str | None = None, shard: str | None = None,
                 tenant: str | None = None, dtype=None,
                 sharding: str = "single",
                 placement: str = "device") -> int:
        """Record an allocation; returns a key for update()/release().
        Labels default from the ambient ``owner()`` scope."""
        own = current_owner()
        e = Entry(
            key=0,
            collection=str(collection if collection is not None
                           else own["collection"]),
            shard=str(shard if shard is not None else own["shard"]),
            tenant=str(tenant if tenant is not None else own["tenant"]),
            component=str(component),
            dtype="" if dtype is None else str(dtype),
            nbytes=max(0, int(nbytes)),
            sharding=sharding,
            placement=placement,
        )
        with self._lock:
            e.key = self._next_key
            self._next_key += 1
            self._entries[e.key] = e
            self._apply_delta(e, e.nbytes)
        return e.key

    def update(self, key: int, nbytes: int) -> None:
        """Resize an existing entry (capacity grow / shrink-on-compact)."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return
            delta = max(0, int(nbytes)) - e.nbytes
            e.nbytes += delta
            self._apply_delta(e, delta)

    def release(self, key: int) -> None:
        with self._lock:
            e = self._entries.pop(key, None)
            if e is None:
                return
            self._apply_delta(e, -e.nbytes)

    def release_many(self, keys) -> None:
        """Finalizer-friendly bulk release (missing keys are fine). Takes
        the live list object so keys added after finalize() registration
        are still honored."""
        for k in list(keys):
            self.release(k)

    def set_keyed(self, keys: dict, component: str, nbytes: int, *,
                  owner: dict | None = None, dtype=None,
                  sharding: str = "single",
                  placement: str = "device") -> None:
        """Upsert helper for stores that re-publish a component's size
        across grows: ``keys`` maps component -> ledger key and is owned
        by the caller (pass the same dict to a weakref finalizer via
        ``release_many(keys.values())`` for cleanup-on-drop)."""
        key = keys.get(component)
        if key is None:
            if nbytes <= 0:
                return
            keys[component] = self.register(
                component, nbytes, dtype=dtype, sharding=sharding,
                placement=placement, **(owner or {}))
        else:
            self.update(key, max(0, int(nbytes)))

    def track(self, component: str, array, **labels) -> int | None:
        """Register ``array.nbytes`` and auto-release when the array is
        garbage-collected (weakref.finalize) — the right lifetime for
        transient device buffers like packed allow bitmasks. Returns the
        key, or None when the object cannot carry a weakref (the entry
        is then not recorded rather than leaked)."""
        nbytes = int(getattr(array, "nbytes", 0))
        if nbytes <= 0:
            return None
        key = self.register(component, nbytes,
                            dtype=getattr(array, "dtype", None), **labels)
        try:
            weakref.finalize(array, self.release, key)
        except TypeError:
            self.release(key)
            return None
        return key

    # -- internals ------------------------------------------------------------

    def _apply_delta(self, e: Entry, delta: int) -> None:
        """Caller holds ``_lock``. Gauges are updated outside-in: the
        metric child has its own lock, and we never call back into the
        ledger from there."""
        if delta == 0:
            return
        if e.placement != "device":
            # host-tier entries (e.g. the HNSW graph) show in the
            # breakdown endpoint only — the hbm_* gauges and the
            # admission totals are DEVICE bytes by contract
            return
        self._device_total += delta
        if self._device_total > self._device_peak:
            self._device_peak = self._device_total
        self._by_collection[e.collection] = \
            self._by_collection.get(e.collection, 0) + delta
        if self._by_collection[e.collection] <= 0:
            del self._by_collection[e.collection]
        sk = (e.collection, e.shard)
        self._by_shard[sk] = self._by_shard.get(sk, 0) + delta
        if self._by_shard[sk] <= 0:
            del self._by_shard[sk]
        gk = (e.collection, e.shard, e.component)
        self._by_gauge[gk] = self._by_gauge.get(gk, 0) + delta
        gauge_val = self._by_gauge[gk]
        if gauge_val <= 0:
            del self._by_gauge[gk]
        self._export_gauges(gk, gauge_val)

    def _export_gauges(self, gk: tuple, gauge_val: int) -> None:
        try:
            from weaviate_tpu.runtime.metrics import (hbm_bytes,
                                                      hbm_peak_bytes)

            if gauge_val <= 0:
                hbm_bytes.remove(*gk)
            else:
                hbm_bytes.labels(*gk).set(float(gauge_val))
            hbm_peak_bytes.set(float(self._device_peak))
        except Exception:  # noqa: BLE001 — accounting must never fail allocs
            pass

    # -- queries --------------------------------------------------------------

    def total_bytes(self) -> int:
        """Live device bytes across every registration (the projection
        ``check_device_alloc`` uses when allocator stats are absent)."""
        with self._lock:
            return self._device_total

    def peak_bytes(self) -> int:
        with self._lock:
            return self._device_peak

    def collection_bytes(self, collection: str) -> int:
        with self._lock:
            return self._by_collection.get(str(collection), 0)

    def shard_bytes(self, collection: str, shard: str) -> int:
        with self._lock:
            return self._by_shard.get((str(collection), str(shard)), 0)

    def shard_component_bytes(self, collection: str, shard: str) -> dict:
        """Component -> device bytes for one shard. Epoch stores label
        per epoch (``corpus@e3``, ``codes@e3``), so this is how the
        epoch policy (and its tests) see exactly which epoch owns which
        bytes — and that compaction/migration actually released them."""
        collection, shard = str(collection), str(shard)
        with self._lock:
            return {comp: b for (c, s, comp), b in self._by_gauge.items()
                    if c == collection and s == shard}

    def set_host_count(self, n_hosts: int) -> None:
        """Record the mesh's host count (server startup / Database
        init) so ``refresh_host_gauge`` can run from scrape handlers
        without reaching back to the mesh."""
        with self._lock:
            self._host_count_hint = max(1, int(n_hosts))

    def refresh_host_gauge(self) -> dict:
        """Scrape-time refresh of ``weaviate_tpu_hbm_host_bytes`` (the
        perfgate.refresh pattern): the split depends on LIVE totals, so
        recomputing at exposition keeps the gauge summing exactly to
        the live device total instead of whatever the last REST read
        left behind."""
        return self.host_rollup(self._host_count_hint)

    def host_rollup(self, n_hosts: int) -> dict:
        """Per-HOST device bytes for the hierarchical mesh (ISSUE 13):
        ``{"host-0": bytes, ...}`` that SUMS EXACTLY to
        ``total_bytes()`` — the attribution /v1/nodes and the
        ``weaviate_tpu_hbm_host_bytes`` gauge report, and what the
        placement hook ranks hosts by.

        Attribution follows each entry's LOGICAL-bytes contract:
        row-"sharded" and "replicated" entries split evenly across
        hosts (row-sharding is equal by construction —
        ``shardable_capacity`` — and a replicated array's logical bytes
        are counted once, so an even split keeps the sum invariant;
        the per-device replication overhead already shows up only in
        the allocator-vs-ledger delta); "single"-device entries and
        compile estimates land on host-0, where device 0 lives.
        Integer remainders go to host-0 so the sum is exact."""
        n_hosts = max(1, int(n_hosts))
        out = {f"host-{i}": 0 for i in range(n_hosts)}
        with self._lock:
            entries = [(e.sharding, e.nbytes) for e in
                       self._entries.values() if e.placement == "device"]
        for sharding, nbytes in entries:
            if n_hosts > 1 and sharding in ("sharded", "replicated"):
                share = nbytes // n_hosts
                for i in range(n_hosts):
                    out[f"host-{i}"] += share
                out["host-0"] += nbytes - share * n_hosts
            else:
                out["host-0"] += nbytes
        try:
            from weaviate_tpu.runtime.metrics import hbm_host_bytes

            for host, b in out.items():
                hbm_host_bytes.labels(host).set(float(b))
        except Exception:  # noqa: BLE001 — accounting must never fail reads
            pass
        return out

    def breakdown(self) -> dict:
        """Per-collection rollup: bytes by collection, with nested shard
        and component splits. Device placement only (host-tier entries —
        e.g. HNSW graph arrays — roll up under ``hostBytes``)."""
        with self._lock:
            entries = list(self._entries.values())
        out: dict[str, dict] = {}
        for e in entries:
            col = out.setdefault(e.collection, {
                "bytes": 0, "hostBytes": 0, "shards": {}, "components": {}})
            if e.placement == "device":
                col["bytes"] += e.nbytes
                col["shards"][e.shard] = \
                    col["shards"].get(e.shard, 0) + e.nbytes
            else:
                col["hostBytes"] += e.nbytes
            col["components"][e.component] = \
                col["components"].get(e.component, 0) + e.nbytes
        return out

    def top(self, n: int = 20) -> list[dict]:
        """Largest live allocations, for the debug endpoint."""
        with self._lock:
            entries = sorted(self._entries.values(),
                             key=lambda e: e.nbytes, reverse=True)[:n]
        return [{
            "collection": e.collection, "shard": e.shard,
            "tenant": e.tenant, "component": e.component,
            "dtype": e.dtype, "nbytes": e.nbytes,
            "sharding": e.sharding, "placement": e.placement,
        } for e in entries]

    def snapshot(self) -> dict:
        """Full debug-endpoint payload body (totals + rollup + top)."""
        return {
            "totalBytes": self.total_bytes(),
            "peakBytes": self.peak_bytes(),
            "collections": self.breakdown(),
            "top": self.top(),
        }

    def reset(self) -> None:
        """Drop every entry (tests)."""
        with self._lock:
            entries = list(self._entries)
        for k in entries:
            self.release(k)
        with self._lock:
            self._device_peak = self._device_total


#: process-wide default ledger (one per node, like the metrics registry)
ledger = HBMLedger()
