"""Request-scoped tracing with device-time attribution.

The reference exports ~70 Prometheus vecs but no per-request breakdown;
aggregate histograms can't say whether a slow hybrid query spent its time
in batching wait, host->device transfer, the Pallas scan, the ICI merge,
the cross-node scatter-gather, or the LSM object fetch. Worse, on an
async-dispatch runtime wall clock at the REST layer actively
MISATTRIBUTES device time: a dispatch returns as soon as the work is
enqueued, so the cost surfaces in whatever later blocks on the result
(usually ``np.asarray`` in an unrelated span).

Design:

- ``trace(name)`` opens a request root; ``span(name, **attrs)`` nests
  under whatever is current via a contextvar. Outside a trace ``span``
  is a no-op yielding a shared null span — instrumentation points cost
  one contextvar read on untraced paths.
- Cheap (host-clock) spans are ALWAYS recorded inside a trace. Device
  timing is the expensive part: ``device_sync(sp, *arrays)`` calls
  ``jax.block_until_ready`` ONLY when the trace is *sampled* (1-in-N
  per-process counter from TRACE_SAMPLE_RATE, or forced per request via
  ``?trace=true``). Unsampled requests take no device synchronization.
- Finished traces land in an in-memory ring buffer served by
  ``GET /v1/debug/traces``; roots slower than the slow-query threshold
  (QUERY_SLOW_LOG_ENABLED/QUERY_SLOW_LOG_THRESHOLD, reference:
  helpers/slow_queries.go) are logged with their span breakdown.
- Cross-node stitching: ``current_traceparent()`` emits a W3C-style
  ``00-{trace}-{span}-{flags}`` header the cluster transport forwards;
  the receiving node adopts it via ``remote_segment`` and EXPORTS its
  finished spans back in the RPC response, which the caller ``absorb``s
  into the live trace — one stitched trace per distributed query even
  across real process boundaries.
- Worker-thread propagation: ``contextvars`` do not flow into
  ``ThreadPoolExecutor`` workers; ``propagate(fn)`` captures the current
  (trace, span) and reinstates it around ``fn`` (used by the collection
  scatter-gather pool, the hybrid legs and the 2PC broadcast), and
  ``capture()``/``run_in`` do the same for the query batcher whose one
  dispatch serves many waiters.

Every finished span also feeds the ``weaviate_tpu_span_duration_seconds``
histogram (runtime/metrics.py) so traces and /metrics stay consistent.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import random
import threading
import time
from collections import deque

logger = logging.getLogger(__name__)
slow_logger = logging.getLogger("weaviate_tpu.slow_query")

# active (trace, span) for this context; None outside a request
_current: contextvars.ContextVar = contextvars.ContextVar(
    "weaviate_tpu_trace", default=None)

# ids need uniqueness, not cryptography: uuid4 hits the urandom syscall
# (~100us on some kernels) THREE times per traced request — a PRNG
# seeded once from urandom is ~100x cheaper. getrandbits on a shared
# Random is a single C call, atomic under the GIL.
_rng = random.Random(int.from_bytes(os.urandom(16), "big"))


def _new_id(nbytes: int) -> str:
    return format(_rng.getrandbits(nbytes * 8), f"0{nbytes * 2}x")


class Span:
    """One timed operation. Mutable while open; serialized into its
    trace's span list (as a plain dict) when it finishes."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attrs",
                 "start_ms", "duration_ms", "_t0")

    def __init__(self, trace_id: str, parent_id: str | None, name: str,
                 attrs: dict, start_ms: float):
        self.trace_id = trace_id
        self.span_id = _new_id(8)
        self.parent_id = parent_id
        self.name = name
        self.attrs = attrs
        self.start_ms = start_ms
        self.duration_ms = 0.0
        self._t0 = time.perf_counter()

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ms": round(self.start_ms, 3),
            "duration_ms": round(self.duration_ms, 3),
            "attrs": {k: _jsonable(v) for k, v in self.attrs.items()},
        }


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


class _NullSpan:
    """Shared no-op span yielded outside any trace."""

    __slots__ = ()

    def set(self, **attrs) -> None:
        pass


NULL_SPAN = _NullSpan()


class Trace:
    """Collects finished spans for one request (or one remote segment of
    a distributed request). Span appends are cross-thread safe."""

    MAX_SPANS = 512  # bound memory when an instrumented loop runs hot

    __slots__ = ("trace_id", "sampled", "spans", "dropped", "started_at",
                 "_t0", "remote", "_lock")

    def __init__(self, trace_id: str, sampled: bool, remote: bool = False):
        self.trace_id = trace_id
        self.sampled = sampled
        self.remote = remote
        self.spans: list[dict] = []
        self.dropped = 0
        self.started_at = time.time()
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()

    def now_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1000.0

    def add(self, span_dict: dict) -> None:
        with self._lock:
            if len(self.spans) >= self.MAX_SPANS:
                self.dropped += 1
                return
            self.spans.append(span_dict)

    def to_dict(self) -> dict:
        with self._lock:
            spans = sorted(self.spans, key=lambda s: s["start_ms"])
            dropped = self.dropped
        out = {
            "trace_id": self.trace_id,
            "sampled": self.sampled,
            "started_at": self.started_at,
            "spans": spans,
        }
        if dropped:
            out["dropped_spans"] = dropped
        return out


# -- sampling policy ----------------------------------------------------------

_sample_lock = threading.Lock()
_sample_counter = 0
_sample_every: int | None = None  # None = not yet read from the env


def _compute_sample_every() -> int:
    """0 = never, 1 = always, N = every Nth request."""
    raw = os.environ.get("TRACE_SAMPLE_RATE", "0").strip()
    try:
        rate = float(raw)
    except ValueError:
        logger.warning("TRACE_SAMPLE_RATE=%r is not a float; tracing "
                       "device sampling disabled", raw)
        return 0
    if rate <= 0.0:
        return 0
    if rate >= 1.0:
        return 1
    return max(1, round(1.0 / rate))


def should_sample() -> bool:
    """Per-process deterministic 1-in-N sampler (cheaper and steadier
    under load than per-request randomness)."""
    global _sample_counter, _sample_every
    if _sample_every is None:
        _sample_every = _compute_sample_every()
    if _sample_every == 0:
        return False
    with _sample_lock:
        _sample_counter += 1
        return _sample_counter % _sample_every == 0


# -- slow-query log -----------------------------------------------------------

_slow_threshold: float | None = None  # seconds; 0 = disabled; None = unread


def _compute_slow_threshold() -> float:
    from weaviate_tpu.config import _flag

    if not _flag(os.environ, "QUERY_SLOW_LOG_ENABLED"):
        return 0.0
    raw = os.environ.get("QUERY_SLOW_LOG_THRESHOLD", "2s").strip()
    try:
        if raw.endswith("ms"):
            return float(raw[:-2]) / 1000.0
        if raw.endswith("s"):
            return float(raw[:-1])
        return float(raw)
    except ValueError:
        return 2.0


def _get_slow_threshold() -> float:
    global _slow_threshold
    if _slow_threshold is None:
        _slow_threshold = _compute_slow_threshold()
    return _slow_threshold


def get_slow_threshold() -> float:
    """Public accessor for the lazily-cached slow-query threshold
    (seconds; 0 = disabled) — the one source for QUERY_SLOW_LOG_*."""
    return _get_slow_threshold()


def reset_policy_for_tests() -> None:
    """Re-read TRACE_SAMPLE_RATE / slow-log env on next use."""
    global _sample_every, _slow_threshold, _sample_counter
    _sample_every = None
    _slow_threshold = None
    _sample_counter = 0


# -- finished-trace ring buffer -----------------------------------------------

_RING_SIZE = 256
_ring: deque = deque(maxlen=_RING_SIZE)
_ring_lock = threading.Lock()


def recent_traces(limit: int = 50) -> list[dict]:
    """Newest-first finished traces for GET /v1/debug/traces."""
    with _ring_lock:
        items = list(_ring)
    return items[::-1][: max(0, limit)]


def clear_traces() -> None:
    with _ring_lock:
        _ring.clear()


# -- span plumbing ------------------------------------------------------------

def _observe_metric(name: str, duration_s: float) -> None:
    try:
        from weaviate_tpu.runtime.metrics import span_duration

        span_duration.labels(name).observe(duration_s)
    except Exception:  # metrics must never fail a request
        pass


def _finish(tr: Trace, sp: Span) -> None:
    sp.duration_ms = (time.perf_counter() - sp._t0) * 1000.0
    tr.add(sp.to_dict())
    _observe_metric(sp.name, sp.duration_ms / 1000.0)


@contextlib.contextmanager
def trace(name: str, force: bool = False, **attrs):
    """Open a request root trace. Nested calls degrade to plain spans so
    layered entry points (REST -> gRPC handler reuse) compose."""
    if _current.get() is not None:
        with span(name, **attrs) as sp:
            yield sp
        return
    tr = Trace(_new_id(16), sampled=force or should_sample())
    root = Span(tr.trace_id, None, name, dict(attrs), 0.0)
    token = _current.set((tr, root))
    try:
        yield root
    finally:
        _finish(tr, root)
        _current.reset(token)
        _finalize(tr, root)


def _finalize(tr: Trace, root: Span) -> None:
    d = tr.to_dict()
    with _ring_lock:
        _ring.append(d)
    # tail-based retention (ISSUE 15): the keep/drop decision happens at
    # COMPLETION — inside an edge timeline the tailboard attaches this
    # trace and decides when the timeline closes (status known); outside
    # one it makes a standalone slow/fault decision
    try:
        from weaviate_tpu.runtime import tailboard

        tailboard.on_trace_complete(d, root.name, root.duration_ms)
    except Exception:  # observability must never fail the request
        pass
    threshold = _get_slow_threshold()
    took = root.duration_ms / 1000.0
    if threshold > 0 and took >= threshold:
        # structured slowlog (ISSUE 15 satellite): one machine-parseable
        # line AND a retrievable entry in the flight recorder's slowlog
        # ring (/v1/debug/flight) instead of free text only
        record = {
            "trace_id": tr.trace_id,
            "root": root.name,
            "duration_ms": round(root.duration_ms, 3),
            "threshold_ms": round(threshold * 1000.0, 3),
            "spans": [
                {"name": s["name"],
                 "duration_ms": round(s["duration_ms"], 3)}
                for s in sorted(tr.spans,
                                key=lambda s: -s["duration_ms"])[:8]],
        }
        import json as _json

        slow_logger.warning("slow_query %s", _json.dumps(record))
        try:
            from weaviate_tpu.runtime import tailboard

            tailboard.slow_root(record)
        except Exception:
            pass


class _SpanCM:
    """Class-based context manager (not @contextmanager: the generator
    machinery costs ~2x on the no-op path, and span() sits on query hot
    paths where it usually IS a no-op)."""

    __slots__ = ("name", "attrs", "_pair", "_token")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        cur = _current.get()
        if cur is None:
            self._pair = None
            return NULL_SPAN
        tr, parent = cur
        sp = Span(tr.trace_id, parent.span_id, self.name, self.attrs,
                  tr.now_ms())
        self._pair = (tr, sp)
        self._token = _current.set((tr, sp))
        return sp

    def __exit__(self, *exc):
        if self._pair is None:
            return False
        tr, sp = self._pair
        _finish(tr, sp)
        _current.reset(self._token)
        return False


def span(name: str, **attrs) -> _SpanCM:
    """Nested span under the current trace; no-op outside one."""
    return _SpanCM(name, attrs)


def record_span(name: str, start_s: float, end_s: float, **attrs) -> None:
    """Record an externally-timed span (perf_counter stamps) under the
    current span — how the query batcher's worker-side timings land in
    each waiter's trace without the worker holding their contexts."""
    cur = _current.get()
    if cur is None:
        return
    tr, parent = cur
    start_ms = (start_s - tr._t0) * 1000.0
    tr.add({
        "name": name,
        "span_id": _new_id(8),
        "parent_id": parent.span_id,
        "start_ms": round(start_ms, 3),
        "duration_ms": round((end_s - start_s) * 1000.0, 3),
        "attrs": {k: _jsonable(v) for k, v in attrs.items()},
    })
    _observe_metric(name, max(0.0, end_s - start_s))


def is_active() -> bool:
    return _current.get() is not None


def is_sampled() -> bool:
    cur = _current.get()
    return cur is not None and cur[0].sampled


def current_timing() -> list[dict]:
    """Spans recorded so far in the live trace (for per-query
    ``_debug.timing`` response breakdowns; the root is still open)."""
    cur = _current.get()
    if cur is None:
        return []
    tr, _ = cur
    with tr._lock:
        return sorted(list(tr.spans), key=lambda s: s["start_ms"])


def current_trace_id() -> str | None:
    cur = _current.get()
    return None if cur is None else cur[0].trace_id


# -- device-time attribution --------------------------------------------------

def device_sync(sp, *values) -> None:
    """Attribute device time to ``sp`` by blocking until ``values`` (jax
    arrays / pytrees) materialize — ONLY on sampled traces, so unsampled
    requests never add a device synchronization point."""
    cur = _current.get()
    if cur is None or not cur[0].sampled:
        return
    vals = [v for v in values if v is not None]
    if not vals:
        return
    try:
        import jax

        t0 = time.perf_counter()
        jax.block_until_ready(vals)
        sp.set(device_ms=round((time.perf_counter() - t0) * 1000.0, 3))
    except Exception:  # best-effort: a poisoned buffer raises at asarray
        pass


# -- device->host boundary ----------------------------------------------------

def d2h(*values):
    """THE sanctioned device->host transfer at the API boundary.

    Fetches ``values`` (jax arrays; ``None`` entries pass through) to
    numpy under a ``transfer.d2h`` span. Like ``device_sync``, the
    device wait is ATTRIBUTED (``device_ms``) only on sampled traces —
    there the device completion is timed separately (block_until_ready)
    from the host-side copy, so the span splits chip time from memcpy
    time. Unsampled/untraced callers still pay the transfer (that is the
    point of calling this), just without the extra sync for attribution.

    Hot-path modules (engine/, ops/, parallel/, the query batcher) must
    not fetch device values themselves (graftlint G1); they return
    device-resident handles (runtime/transfer.py) whose ``result()``
    funnels through here — one audited boundary instead of scattered
    ``np.asarray`` syncs.
    """
    import numpy as _np

    n_arrays = sum(1 for v in values if v is not None)
    with span("transfer.d2h", arrays=n_arrays) as sp:
        cur = _current.get()
        synced = False
        if cur is not None and cur[0].sampled and n_arrays:
            try:
                import jax

                t0 = time.perf_counter()
                jax.block_until_ready([v for v in values if v is not None])
                sp.set(device_ms=round(
                    (time.perf_counter() - t0) * 1000.0, 3))
                synced = True
            except Exception:  # a poisoned buffer raises at asarray below
                pass
        t_copy = time.perf_counter()
        out = tuple(None if v is None else _np.asarray(v) for v in values)
        if synced:
            # the device wait above already drained, so the asarray loop
            # here is (close to) pure memcpy — the only place the
            # device/copy split of a D2H window is directly measurable.
            # Kernelscope's EWMA turns these sampled splits into the
            # memcpy subtraction that makes the UNsampled drain-stamp
            # attribution honest.
            try:
                from weaviate_tpu.runtime import kernelscope

                kernelscope.observe_memcpy(
                    time.perf_counter() - t_copy,
                    sum(a.nbytes for a in out if a is not None))
            except Exception:
                pass
    return out


# -- cross-thread propagation -------------------------------------------------

def capture():
    """Opaque context handle for run_in (None outside a trace)."""
    return _current.get()


def run_in(ctx, fn, *args, **kwargs):
    """Run ``fn`` under a captured (trace, span) context."""
    if ctx is None:
        return fn(*args, **kwargs)
    token = _current.set(ctx)
    try:
        return fn(*args, **kwargs)
    finally:
        _current.reset(token)


def annotate(**attrs) -> None:
    """Attach attrs to the CURRENT span (no-op outside a trace) — how
    cross-cutting layers (faultline injections, degraded-read markers)
    tag whatever span happens to be active."""
    cur = _current.get()
    if cur is not None:
        cur[1].set(**attrs)


def propagate(fn):
    """Wrap ``fn`` to carry the CURRENT request context into worker
    threads (pool.map / Thread targets don't inherit contextvars).
    Carries the whole request quad: trace span, deadline budget, the
    degraded-marker sink, and the faultline node identity — a shard
    fan-out thread must spend the same budget, report into the same
    response, and issue its RPCs AS the same cluster node (the
    partition topology layer cuts links by (src, dst) node pair)."""
    from weaviate_tpu.runtime import degrade, faultline, retry

    ctx = _current.get()
    dl = retry.current_deadline()
    markers = degrade.current_markers()
    node = faultline.current_node()
    if ctx is None and dl is None and markers is None and node is None:
        return fn

    def wrapper(*args, **kwargs):
        tokens = (retry.set_deadline(dl), degrade.set_markers(markers))
        try:
            with faultline.node_scope(node):
                return run_in(ctx, fn, *args, **kwargs)
        finally:
            retry.reset_deadline(tokens[0])
            degrade.reset_markers(tokens[1])

    return wrapper


# -- traceparent propagation (cluster transport) ------------------------------

def current_traceparent() -> str | None:
    """W3C-shaped ``00-{trace_id}-{span_id}-{flags}`` naming the CURRENT
    span as the remote parent; flags bit 0 carries the sampled decision."""
    cur = _current.get()
    if cur is None:
        return None
    tr, sp = cur
    return f"00-{tr.trace_id}-{sp.span_id}-{'01' if tr.sampled else '00'}"


def parse_traceparent(header: str | None):
    """-> (trace_id, parent_span_id, sampled) or None on any malformation
    (an unparseable header must never fail the RPC carrying it)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _ver, trace_id, parent_id, flags = parts
    if not trace_id or not parent_id:
        return None
    return trace_id, parent_id, flags == "01"


class RemoteSegment:
    """Handle yielded by ``remote_segment``: after the block exits,
    ``export()`` returns the segment's finished spans for the RPC
    response (None when there is nothing to ship)."""

    __slots__ = ("_trace",)

    def __init__(self, tr: Trace | None):
        self._trace = tr

    MAX_EXPORT = 64  # response-header budget

    def export(self) -> list[dict] | None:
        if self._trace is None:
            return None
        with self._trace._lock:
            spans = list(self._trace.spans)[: self.MAX_EXPORT]
        return spans or None


@contextlib.contextmanager
def remote_segment(traceparent: str | None, name: str = "rpc.server",
                   **attrs):
    """Adopt an incoming traceparent on the serving node: spans recorded
    inside chain to the caller's span id and are EXPORTED (via
    ``RemoteSegment``) instead of entering the local ring — the caller
    absorbs them, yielding one stitched trace."""
    parsed = parse_traceparent(traceparent)
    if parsed is None or _current.get() is not None:
        # no incoming context (or already tracing in-process): plain span
        with span(name, **attrs):
            yield RemoteSegment(None)
        return
    trace_id, parent_id, sampled = parsed
    tr = Trace(trace_id, sampled=sampled, remote=True)
    root = Span(trace_id, parent_id, name, dict(attrs), 0.0)
    token = _current.set((tr, root))
    try:
        yield RemoteSegment(tr)
    finally:
        _finish(tr, root)
        _current.reset(token)


def absorb(span_dicts: list[dict], base_ms: float = 0.0) -> None:
    """Merge spans exported by a remote segment into the live trace.
    ``base_ms``: the caller-side start of the RPC span, used to shift the
    remote segment's relative clock onto this trace's timeline."""
    cur = _current.get()
    if cur is None:
        return
    tr, _ = cur
    for d in span_dicts:
        if not isinstance(d, dict) or "name" not in d:
            continue
        shifted = dict(d)
        try:
            shifted["start_ms"] = round(float(d.get("start_ms", 0.0))
                                        + base_ms, 3)
        except (TypeError, ValueError):
            shifted["start_ms"] = base_ms
        attrs = shifted.get("attrs")
        if not isinstance(attrs, dict):  # corrupt spans must not fail the RPC
            attrs = {}
        shifted["attrs"] = {**attrs, "remote": True}
        tr.add(shifted)
