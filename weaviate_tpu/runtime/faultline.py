"""faultline: deterministic fault injection at named boundaries.

Every cross-node and cross-device boundary in this codebase has a
failure story only if it can be MADE to fail on demand. This registry
names those boundaries as fault points; production code calls
``fire("point", ...)`` at each one. Disarmed (the default, and the only
state outside tests/the chaos harness) that call is a read of one module
global and an immediate return — nothing allocates, nothing locks, no
schedule lookup happens, so the serving hot path pays a single
predictable branch (the ``served_pipeline`` bench band is the proof).

Armed, a fault point executes a DETERMINISTIC schedule — "fail the 3rd
call", "every 4th call", "calls 2 and 5", or a seeded Bernoulli draw —
so a chaos test that fails replays bit-for-bit from its seed. Supported
actions:

- ``error``    raise (default ``FaultInjected``; sites map it to their
               domain error exactly like a real failure)
- ``latency``  sleep ``latency_s`` then continue
- ``drop``     returned as a directive: the site completes the send but
               discards the reply (the 2PC "prepare landed, ack lost"
               scenario a timeout alone cannot produce)
- ``corrupt``  returned as a directive: the site damages the payload
               (transport garbles the response body; kv flips bytes)
- ``crash``    ``os._exit(exit_code)`` (default 137, the SIGKILL status)
               right at the fault point — the crashpoint primitive the
               kill-restart-verify harness (tools/crashtest) schedules
               at every persistence boundary
- ``torn``     returned as the Schedule itself: the site (fsutil's
               ``guarded_write`` file wrapper) writes the first
               ``torn_bytes`` bytes of its payload, flushes, then
               ``os._exit`` — a genuinely partial frame on disk,
               simulating process death mid-``write(2)``

Every injection bumps ``weaviate_tpu_fault_injected_total{point,action}``
and annotates the active trace span, so a chaos run can assert that the
metrics/span plumbing accounts for every fault it scheduled (``crash``/
``torn`` injections die before any assert — their ledger is the on-disk
state the harness verifies after restart).

Known fault points (grep for ``faultline.fire`` to verify):

==========================  ==================================================
point                       boundary
==========================  ==================================================
``transport.rpc.send``      every intra-cluster HTTP RPC (cluster/transport)
``remote.shard_op``         RemoteShardClient data-plane ops (cluster/remote)
``replication.prepare``     2PC prepare, per replica (incl. local short-circuit)
``replication.commit``      2PC commit, per replica (incl. local short-circuit)
``kv.get_many``             batched LSM point lookups (storage/kv)
``transfer.d2h``            the sanctioned device->host fetch (runtime/transfer)
``batcher.dispatch``        one coalesced device dispatch (runtime/query_batcher)
``wal.append.pre_fsync``    WAL frame written (tear-able), before fsync
``wal.append.post_fsync``   WAL frame durable, before the ack returns
``wal.create``              new WAL file minted, before its dir entry is synced
``segment.write.mid``       per record inside a segment write (tear-able)
``segment.write.pre_rename``segment bytes fsynced, before os.replace
``segment.post_rename``     segment renamed+dir-synced, before WAL delete
``raft.persist.meta``       before (term, votedFor) hits the raft bucket
``raft.persist.log``        before a log batch hits the raft bucket
``raft.persist.snapshot``   before the FSM snapshot hits the raft bucket
``hnsw.snap.pre_replace``   HNSW snapshot fsynced, before os.replace
``hnsw.snap.post_replace``  snapshot durable, before the op-log reset
==========================  ==================================================

Beyond per-call fault points, the TOPOLOGY layer (bottom of this module)
models cluster-scale network partitions as a set of DIRECTED link rules
over (src, dst) node pairs, consulted by ``cluster/transport.rpc`` on
every intra-cluster call. A cut request direction fails like an
unreachable peer; a cut *reply* direction lets the server execute the
handler and loses the ack — which is how a one-way partition actually
behaves over an HTTP transport, and what the asymmetric raft scenarios
("leader can send but not receive") need. Rules are scheduled
deterministically in consult counts (``after``/``duration`` windows,
``period``/``duty`` flapping, seeded Bernoulli) and are armable through
``WEAVIATE_TPU_FAULTLINE`` in subprocess nodes like every other
schedule.
"""

from __future__ import annotations

import contextvars
import os
import random
import threading
import time
from contextlib import contextmanager

#: the disarmed fast path: ``fire`` checks this plain module global
#: before touching anything else. Only arm/disarm mutate it (under
#: ``_lock``); readers tolerate the benign race — a site racing a
#: concurrent arm() simply misses the very first scheduled call.
_ARMED = False

_lock = threading.Lock()
_schedules: dict[str, list["Schedule"]] = {}

#: the persistence crashpoints, in deterministic sweep order — the
#: crashtest harness iterates exactly this tuple, and KNOWN_POINTS is
#: derived from it below, so there is ONE list to maintain
CRASHPOINTS = (
    "wal.append.pre_fsync",
    "wal.append.post_fsync",
    "wal.create",
    "segment.write.mid",
    "segment.write.pre_rename",
    "segment.post_rename",
    "raft.persist.meta",
    "raft.persist.log",
    "raft.persist.snapshot",
    "hnsw.snap.pre_replace",
    "hnsw.snap.post_replace",
)

KNOWN_POINTS = frozenset({
    "transport.rpc.send",
    "remote.shard_op",
    "replication.prepare",
    "replication.commit",
    "kv.get_many",
    "transfer.d2h",
    "batcher.dispatch",
    # epoch migration (db/collection.py migrate_epoch): the three crash
    # windows the no-loss/no-double-serve invariant is tested across —
    # after target ingest, after the durable cutover markers, and after
    # the source delete
    "epoch.migrate.pre_ingest",
    "epoch.migrate.post_ingest",
    "epoch.migrate.post_cutover",
}) | frozenset(CRASHPOINTS)

_ACTIONS = ("error", "latency", "drop", "corrupt", "crash", "torn")


class FaultInjected(RuntimeError):
    """The default injected failure. Sites catch it alongside their real
    transport/IO errors so an injected fault takes the exact code path a
    real one would."""

    def __init__(self, point: str, message: str = ""):
        super().__init__(message or f"faultline: injected fault at {point}")
        self.point = point


class Schedule:
    """One armed fault: which calls at a point fire, and what happens.

    Deterministic by construction — matching depends only on the call
    index (``nth``/``every``/explicit sets) or on a ``random.Random(seed)``
    stream, never on wall time or thread identity."""

    __slots__ = ("point", "action", "nth", "every", "p", "latency_s",
                 "times", "error", "match", "calls", "injected", "_rng",
                 "exit_code", "torn_bytes")

    def __init__(self, point: str, action: str = "error", *,
                 nth: int | tuple | list | set | None = None,
                 every: int | None = None, p: float | None = None,
                 seed: int = 0, latency_s: float = 0.0,
                 times: int | None = None, error=None, match=None,
                 exit_code: int = 137, torn_bytes: int = 0):
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r}; "
                             f"expected one of {_ACTIONS}")
        self.point = point
        self.action = action
        self.nth = ({nth} if isinstance(nth, int) else
                    None if nth is None else set(nth))
        self.every = every
        self.p = p
        self.latency_s = latency_s
        self.times = times
        self.error = error
        self.match = match
        self.exit_code = exit_code   # crash/torn: os._exit status
        self.torn_bytes = torn_bytes  # torn: payload bytes that land
        self.calls = 0     # calls SEEN (armed window only)
        self.injected = 0  # calls actually faulted
        self._rng = random.Random(seed)

    def _selects(self, idx: int) -> bool:
        """Does call ``idx`` (0-based since arming) fire? The Bernoulli
        stream advances on EVERY call so selection is a pure function of
        (seed, idx) regardless of hits."""
        draw = self._rng.random() if self.p is not None else None
        if self.times is not None and self.injected >= self.times:
            return False
        if self.nth is not None:
            return idx in self.nth
        if self.every is not None:
            return (idx + 1) % self.every == 0
        if self.p is not None:
            return draw < self.p
        return True  # no selector = every call (bounded by ``times``)


def arm(point: str, action: str = "error", **kw) -> Schedule:
    """Arm a schedule at a fault point; returns it (``.injected`` is the
    test's ledger). Unknown points raise — a typo'd point would arm a
    fault nothing ever fires."""
    if point not in KNOWN_POINTS:
        raise KeyError(f"unknown fault point {point!r}; known: "
                       f"{sorted(KNOWN_POINTS)}")
    sched = Schedule(point, action, **kw)
    global _ARMED
    with _lock:
        _schedules.setdefault(point, []).append(sched)
        _ARMED = True
    return sched


def disarm(point: str | None = None) -> None:
    """Remove every schedule at ``point`` (all points when None)."""
    global _ARMED
    with _lock:
        if point is None:
            _schedules.clear()
        else:
            _schedules.pop(point, None)
        _ARMED = bool(_schedules)


def armed(point: str | None = None) -> bool:
    if not _ARMED:
        return False
    with _lock:
        return bool(_schedules) if point is None else point in _schedules


@contextmanager
def injected(point: str, action: str = "error", **kw):
    """``with faultline.injected("kv.get_many", nth=0) as sched:`` —
    arm for the block, disarm THIS schedule on exit (other concurrent
    schedules at the same point survive)."""
    sched = arm(point, action, **kw)
    try:
        yield sched
    finally:
        global _ARMED
        with _lock:
            lst = _schedules.get(point)
            if lst is not None:
                try:
                    lst.remove(sched)
                except ValueError:
                    pass
                if not lst:
                    _schedules.pop(point, None)
            _ARMED = bool(_schedules)


def fire(point: str, **attrs) -> str | Schedule | None:
    """The production-side hook. Returns ``None`` (proceed normally), a
    directive string (``"drop"``/``"corrupt"``) the site interprets, or
    the matched :class:`Schedule` for ``action="torn"`` (the site needs
    its ``torn_bytes``/``exit_code``); raises the scheduled error for
    ``action="error"``; ``action="crash"`` never returns — the process
    exits right here, at the boundary the point names. Disarmed this is
    one global read and a return."""
    if not _ARMED:
        return None
    with _lock:
        scheds = list(_schedules.get(point, ()))
    directive: str | Schedule | None = None
    for sched in scheds:
        if sched.match is not None and not sched.match(attrs):
            continue
        with _lock:
            idx = sched.calls
            sched.calls += 1
            hit = sched._selects(idx)
            if hit:
                sched.injected += 1
        if not hit:
            continue
        if sched.action == "crash":
            # no metrics/span recording — the process is gone before any
            # scrape; the on-disk state IS the ledger the harness reads
            os._exit(sched.exit_code)
        _record(point, sched.action, attrs)
        if sched.action == "latency":
            time.sleep(sched.latency_s)
        elif sched.action == "error":
            err = sched.error() if callable(sched.error) else sched.error
            raise err if err is not None else FaultInjected(point)
        elif sched.action == "torn":
            directive = sched
        else:
            directive = sched.action
    return directive


def arm_from_env(var: str = "WEAVIATE_TPU_FAULTLINE",
                 env=None) -> list[Schedule]:
    """Arm schedules described by a JSON env var — the bridge that lets
    the crashtest harness schedule faults in a SUBPROCESS worker it is
    about to kill. Value: a JSON list of Schedule kwargs, e.g.
    ``[{"point": "wal.append.pre_fsync", "action": "crash", "nth": 3}]``.
    Empty/absent arms nothing."""
    import json

    env = os.environ if env is None else env
    raw = env.get(var, "")
    if not raw:
        return []
    specs = json.loads(raw)
    out = []
    for spec in specs:
        spec = dict(spec)
        if "topology" in spec:
            # a partition rule, not a per-point schedule — the bridge
            # that lets a SUBPROCESS cluster node arm its own side of a
            # partition before it even finishes booting
            topo = dict(spec["topology"])
            kind = topo.pop("kind", "partition")
            if kind == "isolate":
                out.extend(isolate(topo.pop("node"), **topo))
            elif kind == "split":
                out.extend(split(topo.pop("a"), topo.pop("b"), **topo))
            else:
                out.extend(partition(**topo))
            continue
        point = spec.pop("point")
        action = spec.pop("action", "error")
        if "nth" in spec and isinstance(spec["nth"], list):
            spec["nth"] = set(spec["nth"])
        out.append(arm(point, action, **spec))
    return out


def _record(point: str, action: str, attrs: dict) -> None:
    """Metric + span annotation for one injection. Import cycles: metrics
    and tracing both sit beside this module, so import lazily and never
    let observability failure mask the injection itself."""
    try:
        from weaviate_tpu.runtime.metrics import fault_injected_total

        fault_injected_total.labels(point, action).inc()
    except Exception:  # pragma: no cover — registry unavailable
        pass
    try:
        from weaviate_tpu.runtime import tracing

        # partition/fault context rides the active span (scalar attrs
        # only — span exports cross node boundaries as JSON)
        extra = {k: v for k, v in attrs.items()
                 if isinstance(v, (str, int, float, bool))}
        tracing.annotate(fault_point=point, fault_action=action, **extra)
    except Exception:  # pragma: no cover
        pass
    try:
        from weaviate_tpu.runtime import tailboard

        # tail retention: a fault fired on the REQUEST thread marks the
        # live timeline directly; worker-thread injections are found by
        # the armed-only span scan at completion instead
        tailboard.note_fault()
    except Exception:  # pragma: no cover
        pass


# -- topology faults: partitions over (src, dst) node pairs --------------------
#
# A partition is a set of DIRECTED link rules: ``LinkRule(src, dst)``
# means packets from ``src`` to ``dst`` are lost while the rule is
# active. ``cluster/transport.rpc`` consults BOTH directions of every
# call: a cut request direction (caller -> callee) makes the call fail
# before anything is sent (an unreachable peer); a cut reply direction
# (callee -> caller) completes the send, lets the remote handler run,
# and loses the ack — the faultline ``drop`` directive, which is how a
# one-way partition really behaves over a request/response transport
# and what "prepare landed, ack lost" / "leader can send but not
# receive" scenarios require.
#
# The caller's identity comes from a contextvar bound by every
# RPC-originating thread (server handler dispatch, raft/gossip loops,
# cycle callbacks, the REST edge; ``tracing.propagate`` carries it onto
# pool threads). Destination names resolve through the addr->name
# registry that gossip membership keeps current. Disarmed (no rules),
# the transport-side check is one module-global read.

#: the disarmed fast path for the topology check — same discipline as
#: ``_ARMED``: plain global, mutated only under ``_topo_lock``
_TOPO_ARMED = False

_topo_lock = threading.Lock()
_links: list["LinkRule"] = []
_addr_names: dict[str, str] = {}  # "host:port" -> node name

_local_node: contextvars.ContextVar = contextvars.ContextVar(
    "weaviate_tpu_faultline_node", default=None)

#: bind this as the node identity for out-of-band harness traffic (the
#: chaos driver's readiness polls, post-mortem probes): topology rules
#: never cut the observer — it is the experimenter's side channel, not
#: part of the cluster under test
OBSERVER = "__observer__"

#: identifies THIS process's topology registry on the wire. The
#: transport's "already checked" header carries it so a server skips
#: its own evaluation only when the client consulted the SAME registry
#: (same process — avoiding double-counted rule consults); when both
#: sides of a cross-process link arm their own rules, each side
#: enforces its own (compositional partition semantics).
PROCESS_TOKEN = f"{os.getpid():x}-{random.getrandbits(32):08x}"


def register_node(name: str, addr: str) -> None:
    """Record a node's advertised transport address so link rules can be
    written over NODE NAMES. Membership calls this for itself and every
    peer it learns; re-registration (an address change) just overwrites."""
    with _topo_lock:
        # drop a stale reverse mapping when a node moves address
        for a, n in list(_addr_names.items()):
            if n == name and a != addr:
                del _addr_names[a]
        _addr_names[addr] = name


def node_for_addr(addr: str) -> str | None:
    with _topo_lock:
        return _addr_names.get(addr)


def bind_node(name: str | None) -> None:
    """Bind the calling context's node identity (which cluster node this
    thread issues RPCs on behalf of). Loop threads bind once at start;
    request-scoped work uses :func:`node_scope`."""
    _local_node.set(name)


def current_node() -> str | None:
    return _local_node.get()


@contextmanager
def node_scope(name: str | None):
    """Bind the node identity for a block (no-op scope when ``name`` is
    None so call sites need no conditional)."""
    if name is None:
        yield
        return
    token = _local_node.set(name)
    try:
        yield
    finally:
        _local_node.reset(token)


class LinkDown(FaultInjected):
    """Injected 'destination unreachable': the request direction of a
    partitioned link. Subclasses FaultInjected so the transport maps it
    to RpcError and feeds the circuit breaker exactly like a real
    connection failure."""

    def __init__(self, src, dst, rule: str):
        super().__init__("topology.link",
                         f"faultline: link {src}->{dst} cut by partition "
                         f"rule {rule!r}")
        self.src, self.dst, self.rule = src, dst, rule


def _match_side(pattern, node) -> bool:
    """``pattern``: "*" (matches anything, incl. an unbound/unknown
    side), a node name, or a list/set/tuple of names."""
    if pattern == "*":
        return True
    if node is None:
        return False
    if isinstance(pattern, (set, frozenset, list, tuple)):
        return node in pattern
    return node == pattern


class LinkRule:
    """One directed link fault: traffic ``src -> dst`` is lost while the
    rule is active. Activity is a deterministic function of the rule's
    own consult counter (every consult of this directed edge bumps it):

    - ``after``:    rule activates at consult index ``after`` (default 0)
    - ``duration``: stays active for this many consults, then is spent
                    (None = until healed)
    - ``period``/``duty``: flapping — within each window of ``period``
                    consults (counted from ``after``) the link is down
                    for the first ``duty`` consults and up for the rest
    - ``p``/``seed``: seeded Bernoulli per consult (composable with the
                    window above; the stream advances every consult so
                    selection is a pure function of (seed, index))
    """

    __slots__ = ("name", "src", "dst", "after", "duration", "period",
                 "duty", "p", "_rng", "consults", "cuts")

    def __init__(self, src, dst, *, name: str = "partition",
                 after: int = 0, duration: int | None = None,
                 period: int | None = None, duty: int | None = None,
                 p: float | None = None, seed: int = 0):
        if period is not None and (duty is None or not 0 < duty <= period):
            raise ValueError("flapping rules need 0 < duty <= period")
        self.name = name
        self.src = tuple(src) if isinstance(src, (list, set)) else src
        self.dst = tuple(dst) if isinstance(dst, (list, set)) else dst
        self.after = after
        self.duration = duration
        self.period = period
        self.duty = duty
        self.p = p
        self._rng = random.Random(seed)
        self.consults = 0  # directed-edge consults seen while armed
        self.cuts = 0      # consults that came back "link down"

    def covers(self, src, dst) -> bool:
        return _match_side(self.src, src) and _match_side(self.dst, dst)

    def _fires(self) -> bool:
        """Caller holds ``_topo_lock``. One consult of this directed
        edge: advance the counter (and the Bernoulli stream), report
        whether the link is down at this index."""
        idx = self.consults
        self.consults += 1
        draw = self._rng.random() if self.p is not None else None
        if idx < self.after:
            return False
        if self.duration is not None and idx >= self.after + self.duration:
            return False
        if self.period is not None \
                and (idx - self.after) % self.period >= self.duty:
            return False
        if self.p is not None and draw >= self.p:
            return False
        self.cuts += 1
        return True

    def snapshot(self) -> dict:
        return {"name": self.name, "src": self.src, "dst": self.dst,
                "after": self.after, "duration": self.duration,
                "period": self.period, "duty": self.duty, "p": self.p,
                "consults": self.consults, "cuts": self.cuts}


def partition(src="*", dst="*", *, symmetric: bool = False,
              **kw) -> list[LinkRule]:
    """Arm a directed link fault (both directions when ``symmetric``).
    Returns the armed rules — their ``cuts`` counters are the test's
    ledger, like ``Schedule.injected``."""
    global _TOPO_ARMED
    rules = [LinkRule(src, dst, **kw)]
    if symmetric:
        rules.append(LinkRule(dst, src, **kw))
    with _topo_lock:
        _links.extend(rules)
        _TOPO_ARMED = True
    return rules


def isolate(node, **kw) -> list[LinkRule]:
    """Symmetric full cut around ``node`` (or a group): nothing in,
    nothing out — the minority-partition primitive."""
    return partition(node, "*", symmetric=True, **kw)


def split(group_a, group_b, **kw) -> list[LinkRule]:
    """Symmetric partition between two groups: every link crossing the
    boundary is cut in both directions; links inside a group stay up."""
    return partition(list(group_a), list(group_b), symmetric=True, **kw)


def heal(name: str | None = None) -> None:
    """Remove partition rules by name (all rules when None). The
    autouse test fixture heals everything between tests, like disarm."""
    global _TOPO_ARMED
    with _topo_lock:
        if name is None:
            _links.clear()
        else:
            _links[:] = [r for r in _links if r.name != name]
        _TOPO_ARMED = bool(_links)


def topology_armed() -> bool:
    return _TOPO_ARMED


def topology_snapshot() -> list[dict]:
    with _topo_lock:
        return [r.snapshot() for r in _links]


def _check_pair(src: str | None, dst: str | None) -> str | None:
    """Verdict for one RPC from ``src`` to ``dst``: consult the request
    direction (src->dst) and the reply direction (dst->src) of every
    rule. Caller already handled the disarmed fast path."""
    if src == OBSERVER:
        return None  # the harness's side channel is never partitioned
    if src is not None and src == dst:
        return None  # a node always reaches itself
    cut_req = cut_reply = False
    names: list[str] = []
    with _topo_lock:
        for rule in _links:
            req = rule.covers(src, dst)
            rep = rule.covers(dst, src)
            if not req and not rep:
                continue
            # exactly ONE consult per rule per RPC — a rule whose
            # patterns cover both directions of this call (wildcards)
            # must not double-bump its counter, or the documented
            # after/duration/period windows halve and the two direction
            # checks draw alternating indices from one stream, breaking
            # seeded replay math. Request direction takes priority when
            # both are covered ("unreachable" wins over "drop" anyway).
            if rule._fires():
                if req:
                    cut_req = True
                else:
                    cut_reply = True
                names.append(rule.name)
    if not cut_req and not cut_reply:
        return None
    verdict = "unreachable" if cut_req else "drop"
    _record("topology.link", verdict,
            {"fault_link": f"{src}->{dst}",
             "fault_partition": ",".join(dict.fromkeys(names))})
    return verdict


def check_link(dst_addr: str, *, src: str | None = None,
               path: str = "") -> str | None:
    """The client-side transport hook: verdict for one RPC about to go
    to ``dst_addr``. Returns None (link up), ``"unreachable"`` (request
    direction cut — fail before sending), or ``"drop"`` (reply
    direction cut — send, let the handler run, lose the ack).
    Disarmed this is one global read and a return."""
    if not _TOPO_ARMED:
        return None
    if src is None:
        src = _local_node.get()
    return _check_pair(src, node_for_addr(dst_addr))


def check_link_incoming(src: str | None, dst: str | None) -> str | None:
    """The SERVER-side hook, for requests whose sender did not consult
    this registry (a subprocess cluster node: its faultline lives in its
    own process). A cut request direction means this request "never
    arrived" — the server closes the connection without dispatching; a
    cut reply direction dispatches the handler and closes without
    answering (the work happened, the ack is lost). Together with the
    client-side check this lets ONE process's partition rules govern a
    mixed in-process + subprocess cluster."""
    if not _TOPO_ARMED:
        return None
    return _check_pair(src, dst)
