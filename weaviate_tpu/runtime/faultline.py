"""faultline: deterministic fault injection at named boundaries.

Every cross-node and cross-device boundary in this codebase has a
failure story only if it can be MADE to fail on demand. This registry
names those boundaries as fault points; production code calls
``fire("point", ...)`` at each one. Disarmed (the default, and the only
state outside tests/the chaos harness) that call is a read of one module
global and an immediate return — nothing allocates, nothing locks, no
schedule lookup happens, so the serving hot path pays a single
predictable branch (the ``served_pipeline`` bench band is the proof).

Armed, a fault point executes a DETERMINISTIC schedule — "fail the 3rd
call", "every 4th call", "calls 2 and 5", or a seeded Bernoulli draw —
so a chaos test that fails replays bit-for-bit from its seed. Supported
actions:

- ``error``    raise (default ``FaultInjected``; sites map it to their
               domain error exactly like a real failure)
- ``latency``  sleep ``latency_s`` then continue
- ``drop``     returned as a directive: the site completes the send but
               discards the reply (the 2PC "prepare landed, ack lost"
               scenario a timeout alone cannot produce)
- ``corrupt``  returned as a directive: the site damages the payload
               (transport garbles the response body; kv flips bytes)
- ``crash``    ``os._exit(exit_code)`` (default 137, the SIGKILL status)
               right at the fault point — the crashpoint primitive the
               kill-restart-verify harness (tools/crashtest) schedules
               at every persistence boundary
- ``torn``     returned as the Schedule itself: the site (fsutil's
               ``guarded_write`` file wrapper) writes the first
               ``torn_bytes`` bytes of its payload, flushes, then
               ``os._exit`` — a genuinely partial frame on disk,
               simulating process death mid-``write(2)``

Every injection bumps ``weaviate_tpu_fault_injected_total{point,action}``
and annotates the active trace span, so a chaos run can assert that the
metrics/span plumbing accounts for every fault it scheduled (``crash``/
``torn`` injections die before any assert — their ledger is the on-disk
state the harness verifies after restart).

Known fault points (grep for ``faultline.fire`` to verify):

==========================  ==================================================
point                       boundary
==========================  ==================================================
``transport.rpc.send``      every intra-cluster HTTP RPC (cluster/transport)
``remote.shard_op``         RemoteShardClient data-plane ops (cluster/remote)
``replication.prepare``     2PC prepare, per replica (incl. local short-circuit)
``replication.commit``      2PC commit, per replica (incl. local short-circuit)
``kv.get_many``             batched LSM point lookups (storage/kv)
``transfer.d2h``            the sanctioned device->host fetch (runtime/transfer)
``batcher.dispatch``        one coalesced device dispatch (runtime/query_batcher)
``wal.append.pre_fsync``    WAL frame written (tear-able), before fsync
``wal.append.post_fsync``   WAL frame durable, before the ack returns
``wal.create``              new WAL file minted, before its dir entry is synced
``segment.write.mid``       per record inside a segment write (tear-able)
``segment.write.pre_rename``segment bytes fsynced, before os.replace
``segment.post_rename``     segment renamed+dir-synced, before WAL delete
``raft.persist.meta``       before (term, votedFor) hits the raft bucket
``raft.persist.log``        before a log batch hits the raft bucket
``raft.persist.snapshot``   before the FSM snapshot hits the raft bucket
``hnsw.snap.pre_replace``   HNSW snapshot fsynced, before os.replace
``hnsw.snap.post_replace``  snapshot durable, before the op-log reset
==========================  ==================================================
"""

from __future__ import annotations

import os
import random
import threading
import time
from contextlib import contextmanager

#: the disarmed fast path: ``fire`` checks this plain module global
#: before touching anything else. Only arm/disarm mutate it (under
#: ``_lock``); readers tolerate the benign race — a site racing a
#: concurrent arm() simply misses the very first scheduled call.
_ARMED = False

_lock = threading.Lock()
_schedules: dict[str, list["Schedule"]] = {}

#: the persistence crashpoints, in deterministic sweep order — the
#: crashtest harness iterates exactly this tuple, and KNOWN_POINTS is
#: derived from it below, so there is ONE list to maintain
CRASHPOINTS = (
    "wal.append.pre_fsync",
    "wal.append.post_fsync",
    "wal.create",
    "segment.write.mid",
    "segment.write.pre_rename",
    "segment.post_rename",
    "raft.persist.meta",
    "raft.persist.log",
    "raft.persist.snapshot",
    "hnsw.snap.pre_replace",
    "hnsw.snap.post_replace",
)

KNOWN_POINTS = frozenset({
    "transport.rpc.send",
    "remote.shard_op",
    "replication.prepare",
    "replication.commit",
    "kv.get_many",
    "transfer.d2h",
    "batcher.dispatch",
    # epoch migration (db/collection.py migrate_epoch): the three crash
    # windows the no-loss/no-double-serve invariant is tested across —
    # after target ingest, after the durable cutover markers, and after
    # the source delete
    "epoch.migrate.pre_ingest",
    "epoch.migrate.post_ingest",
    "epoch.migrate.post_cutover",
}) | frozenset(CRASHPOINTS)

_ACTIONS = ("error", "latency", "drop", "corrupt", "crash", "torn")


class FaultInjected(RuntimeError):
    """The default injected failure. Sites catch it alongside their real
    transport/IO errors so an injected fault takes the exact code path a
    real one would."""

    def __init__(self, point: str, message: str = ""):
        super().__init__(message or f"faultline: injected fault at {point}")
        self.point = point


class Schedule:
    """One armed fault: which calls at a point fire, and what happens.

    Deterministic by construction — matching depends only on the call
    index (``nth``/``every``/explicit sets) or on a ``random.Random(seed)``
    stream, never on wall time or thread identity."""

    __slots__ = ("point", "action", "nth", "every", "p", "latency_s",
                 "times", "error", "match", "calls", "injected", "_rng",
                 "exit_code", "torn_bytes")

    def __init__(self, point: str, action: str = "error", *,
                 nth: int | tuple | list | set | None = None,
                 every: int | None = None, p: float | None = None,
                 seed: int = 0, latency_s: float = 0.0,
                 times: int | None = None, error=None, match=None,
                 exit_code: int = 137, torn_bytes: int = 0):
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r}; "
                             f"expected one of {_ACTIONS}")
        self.point = point
        self.action = action
        self.nth = ({nth} if isinstance(nth, int) else
                    None if nth is None else set(nth))
        self.every = every
        self.p = p
        self.latency_s = latency_s
        self.times = times
        self.error = error
        self.match = match
        self.exit_code = exit_code   # crash/torn: os._exit status
        self.torn_bytes = torn_bytes  # torn: payload bytes that land
        self.calls = 0     # calls SEEN (armed window only)
        self.injected = 0  # calls actually faulted
        self._rng = random.Random(seed)

    def _selects(self, idx: int) -> bool:
        """Does call ``idx`` (0-based since arming) fire? The Bernoulli
        stream advances on EVERY call so selection is a pure function of
        (seed, idx) regardless of hits."""
        draw = self._rng.random() if self.p is not None else None
        if self.times is not None and self.injected >= self.times:
            return False
        if self.nth is not None:
            return idx in self.nth
        if self.every is not None:
            return (idx + 1) % self.every == 0
        if self.p is not None:
            return draw < self.p
        return True  # no selector = every call (bounded by ``times``)


def arm(point: str, action: str = "error", **kw) -> Schedule:
    """Arm a schedule at a fault point; returns it (``.injected`` is the
    test's ledger). Unknown points raise — a typo'd point would arm a
    fault nothing ever fires."""
    if point not in KNOWN_POINTS:
        raise KeyError(f"unknown fault point {point!r}; known: "
                       f"{sorted(KNOWN_POINTS)}")
    sched = Schedule(point, action, **kw)
    global _ARMED
    with _lock:
        _schedules.setdefault(point, []).append(sched)
        _ARMED = True
    return sched


def disarm(point: str | None = None) -> None:
    """Remove every schedule at ``point`` (all points when None)."""
    global _ARMED
    with _lock:
        if point is None:
            _schedules.clear()
        else:
            _schedules.pop(point, None)
        _ARMED = bool(_schedules)


def armed(point: str | None = None) -> bool:
    if not _ARMED:
        return False
    with _lock:
        return bool(_schedules) if point is None else point in _schedules


@contextmanager
def injected(point: str, action: str = "error", **kw):
    """``with faultline.injected("kv.get_many", nth=0) as sched:`` —
    arm for the block, disarm THIS schedule on exit (other concurrent
    schedules at the same point survive)."""
    sched = arm(point, action, **kw)
    try:
        yield sched
    finally:
        global _ARMED
        with _lock:
            lst = _schedules.get(point)
            if lst is not None:
                try:
                    lst.remove(sched)
                except ValueError:
                    pass
                if not lst:
                    _schedules.pop(point, None)
            _ARMED = bool(_schedules)


def fire(point: str, **attrs) -> str | Schedule | None:
    """The production-side hook. Returns ``None`` (proceed normally), a
    directive string (``"drop"``/``"corrupt"``) the site interprets, or
    the matched :class:`Schedule` for ``action="torn"`` (the site needs
    its ``torn_bytes``/``exit_code``); raises the scheduled error for
    ``action="error"``; ``action="crash"`` never returns — the process
    exits right here, at the boundary the point names. Disarmed this is
    one global read and a return."""
    if not _ARMED:
        return None
    with _lock:
        scheds = list(_schedules.get(point, ()))
    directive: str | Schedule | None = None
    for sched in scheds:
        if sched.match is not None and not sched.match(attrs):
            continue
        with _lock:
            idx = sched.calls
            sched.calls += 1
            hit = sched._selects(idx)
            if hit:
                sched.injected += 1
        if not hit:
            continue
        if sched.action == "crash":
            # no metrics/span recording — the process is gone before any
            # scrape; the on-disk state IS the ledger the harness reads
            os._exit(sched.exit_code)
        _record(point, sched.action, attrs)
        if sched.action == "latency":
            time.sleep(sched.latency_s)
        elif sched.action == "error":
            err = sched.error() if callable(sched.error) else sched.error
            raise err if err is not None else FaultInjected(point)
        elif sched.action == "torn":
            directive = sched
        else:
            directive = sched.action
    return directive


def arm_from_env(var: str = "WEAVIATE_TPU_FAULTLINE",
                 env=None) -> list[Schedule]:
    """Arm schedules described by a JSON env var — the bridge that lets
    the crashtest harness schedule faults in a SUBPROCESS worker it is
    about to kill. Value: a JSON list of Schedule kwargs, e.g.
    ``[{"point": "wal.append.pre_fsync", "action": "crash", "nth": 3}]``.
    Empty/absent arms nothing."""
    import json

    env = os.environ if env is None else env
    raw = env.get(var, "")
    if not raw:
        return []
    specs = json.loads(raw)
    out = []
    for spec in specs:
        spec = dict(spec)
        point = spec.pop("point")
        action = spec.pop("action", "error")
        if "nth" in spec and isinstance(spec["nth"], list):
            spec["nth"] = set(spec["nth"])
        out.append(arm(point, action, **spec))
    return out


def _record(point: str, action: str, attrs: dict) -> None:
    """Metric + span annotation for one injection. Import cycles: metrics
    and tracing both sit beside this module, so import lazily and never
    let observability failure mask the injection itself."""
    try:
        from weaviate_tpu.runtime.metrics import fault_injected_total

        fault_injected_total.labels(point, action).inc()
    except Exception:  # pragma: no cover — registry unavailable
        pass
    try:
        from weaviate_tpu.runtime import tracing

        tracing.annotate(fault_point=point, fault_action=action)
    except Exception:  # pragma: no cover
        pass
