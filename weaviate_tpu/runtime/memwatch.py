"""Memory watchdog: gate allocations against host and device budgets.

Reference: usecases/memwatch/monitor.go:49 — CheckAlloc(:99) compares the
projected live heap against GOMEMLIMIT and rejects imports/cache growth
when it would overshoot. The TPU analog adds the HBM budget: device
arrays (vector stores, posting lists) are tracked against per-device HBM
capacity read from jax device memory_stats when available — and, where
the backend exposes no allocator stats (CPU meshes, remote-tunnel TPUs),
against the HBM ledger's projection of registered device bytes
(runtime/hbm_ledger.py), so admission control keeps working exactly
where the allocator goes blind.

Watermark semantics (config: HBM_HIGH_WATERMARK / HBM_LOW_WATERMARK,
defaults 0.9 / 0.8): an import that would push projected usage past
``budget * high`` is refused with a typed 507-style error BEFORE the
transfer is dispatched (no mid-import OOM). Once tripped, the monitor
stays in pressure mode — still refusing — until usage falls back under
``budget * low`` (hysteresis: a budget hovering at the high mark must
not flap accept/reject per request). Every transition and rejection
emits a ``memory.pressure`` trace span and bumps
``weaviate_tpu_memory_pressure_total`` so degradation is visible.
"""

from __future__ import annotations

import os
import threading
import time

#: seconds before an "allocator stats unavailable" verdict is re-probed.
#: One transient failure (backend still initializing) must not disable
#: device stats forever; re-probing every request would re-pay backend
#: init on platforms that genuinely lack stats.
STATS_RETRY_S = 60.0


class InsufficientMemoryError(MemoryError):
    """Typed admission rejection (HTTP maps it to 507 Insufficient
    Storage). ``projected``/``budget``/``source`` describe the refusal."""

    status = 507

    def __init__(self, message: str, *, projected: int = 0,
                 budget: int = 0, source: str = ""):
        super().__init__(message)
        self.projected = projected
        self.budget = budget
        self.source = source  # "allocator" | "ledger" | "tracked"


def _env_fraction(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if 0.0 < v <= 1.0 else default


class MemoryMonitor:
    def __init__(self, host_limit_bytes: int | None = None,
                 device_limit_bytes: int | None = None,
                 max_utilization: float = 0.9,
                 ledger=None,
                 high_watermark: float | None = None,
                 low_watermark: float | None = None):
        self.host_limit = host_limit_bytes
        self.device_limit = device_limit_bytes
        self.max_utilization = max_utilization
        # watermark precedence: explicit arg > env > max_utilization/0.8
        self.high_watermark = (
            high_watermark if high_watermark is not None
            else _env_fraction("HBM_HIGH_WATERMARK", max_utilization))
        self.low_watermark = (
            low_watermark if low_watermark is not None
            else _env_fraction("HBM_LOW_WATERMARK", 0.8))
        self.low_watermark = min(self.low_watermark, self.high_watermark)
        if ledger is None:
            from weaviate_tpu.runtime.hbm_ledger import ledger as _default

            ledger = _default
        self.ledger = ledger
        self._lock = threading.Lock()
        self._pressure = False  # hysteresis latch (high trips, low clears)
        self._last_source = "ledger"  # which tier answered device_in_use
        # host-side tracked allocations (we can't read the Python live
        # heap cheaply; callers register their big buffers)
        self._tracked_host = 0

    # -- device -----------------------------------------------------------

    def device_budget(self, stats: dict | None = None) -> int | None:
        """HBM budget in bytes; explicit limit wins, else read from the
        backend (axon TPU exposes memory_stats), else the
        HBM_DEVICE_LIMIT_BYTES env override (the only option on backends
        with no allocator stats)."""
        budget = self._device_budget_raw(stats)
        try:
            from weaviate_tpu.runtime.metrics import hbm_budget_bytes

            hbm_budget_bytes.set(float(budget or 0))
        except Exception:  # noqa: BLE001 — metrics are best-effort
            pass
        return budget

    def _device_budget_raw(self, stats: dict | None = None) -> int | None:
        if self.device_limit is not None:
            return self.device_limit
        stats = device_memory_stats() if stats is None else stats
        for dev in stats.values():
            if dev.get("bytesLimit"):
                return int(dev["bytesLimit"])
        raw = os.environ.get("HBM_DEVICE_LIMIT_BYTES")
        if raw:
            try:
                return int(raw)
            except ValueError:
                pass
        return None

    def device_in_use(self, stats: dict | None = None) -> int:
        """Current device usage: allocator stats when the backend has
        them, else the ledger's registered device bytes. The ledger
        projection is the LOGICAL global footprint (on a mesh, summed
        over shards) — conservative against a per-device allocator
        budget, exact against an operator-granted
        HBM_DEVICE_LIMIT_BYTES. Records which source answered in
        ``_last_source`` (on a remote-tunnel backend every stats probe
        is an RPC, so the admission path probes ONCE and threads the
        dict through)."""
        stats = device_memory_stats() if stats is None else stats
        in_use = [d["bytesInUse"] for d in stats.values()
                  if d.get("bytesInUse") is not None]
        # _last_source is read by the rejection path on other threads —
        # publish it under the monitor lock (callers never hold it here)
        if in_use:
            with self._lock:
                self._last_source = "allocator"
            return max(in_use)
        with self._lock:
            self._last_source = "ledger"
        return self.ledger.total_bytes()

    def check_device_alloc(self, nbytes: int, what: str = "") -> None:
        """Raise InsufficientMemoryError if landing ``nbytes`` more on the
        device would cross the high watermark (reference CheckAlloc
        semantics: refuse BEFORE allocating, don't OOM mid-import).
        Hysteresis: once tripped, keeps refusing until usage falls under
        the low watermark."""
        # one stats probe serves budget + usage (RPC-priced on tunnels);
        # the explicit-limit fast path skips it entirely
        stats = None if self.device_limit is not None \
            else device_memory_stats()
        budget = self.device_budget(stats)
        if budget is None:
            return
        in_use = self.device_in_use() if stats is None \
            else self.device_in_use(stats)
        source = getattr(self, "_last_source", "ledger")
        projected = in_use + int(nbytes)
        high = budget * self.high_watermark
        low = budget * self.low_watermark
        with self._lock:
            if self._pressure and in_use <= low:
                self._pressure = False
                self._pressure_event("cleared", projected, budget, source)
            reject = projected > high or (self._pressure and projected > low)
            if reject and not self._pressure:
                self._pressure = True
                self._pressure_event("entered", projected, budget, source)
        if reject:
            self._pressure_event("rejected", projected, budget, source,
                                 what=what)
            raise InsufficientMemoryError(
                f"device allocation of {nbytes} bytes"
                f"{f' ({what})' if what else ''} would exceed "
                f"{self.high_watermark:.0%} of HBM budget {budget} "
                f"({source} usage {in_use})",
                projected=projected, budget=budget, source=source)

    @staticmethod
    def _pressure_event(action: str, projected: int, budget: int,
                        source: str, what: str = "") -> None:
        try:
            from weaviate_tpu.runtime import tracing
            from weaviate_tpu.runtime.metrics import memory_pressure_total

            memory_pressure_total.labels("device", action).inc()
            now = time.perf_counter()
            tracing.record_span("memory.pressure", now, now,
                                action=action, projected=projected,
                                budget=budget, source=source,
                                **({"what": what} if what else {}))
        except Exception:  # noqa: BLE001 — observability must not gate
            pass

    @property
    def under_pressure(self) -> bool:
        with self._lock:
            return self._pressure

    # -- host -------------------------------------------------------------

    def track_host(self, nbytes: int) -> None:
        with self._lock:
            self._tracked_host += nbytes

    def release_host(self, nbytes: int) -> None:
        with self._lock:
            self._tracked_host = max(0, self._tracked_host - nbytes)

    def check_host_alloc(self, nbytes: int) -> None:
        if self.host_limit is None:
            return
        with self._lock:
            projected = self._tracked_host + nbytes
        if projected > self.host_limit * self.max_utilization:
            raise InsufficientMemoryError(
                f"host allocation of {nbytes} bytes would exceed "
                f"{self.max_utilization:.0%} of limit {self.host_limit}",
                projected=projected, budget=self.host_limit,
                source="tracked")

    @property
    def tracked_host(self) -> int:
        return self._tracked_host


# "unavailable" verdict with an expiry: a transient probe failure (e.g.
# backend still initializing) re-probes after STATS_RETRY_S instead of
# disabling device stats for the life of the process; a succeeding probe
# clears it. The positive path is NOT cached — allocator stats are a
# cheap attribute read once the backend is up.
_stats_lock = threading.Lock()
_stats_failed_at: float | None = None


def _probe_device_stats() -> dict:
    """One raw probe (module-level so tests can monkeypatch failures)."""
    import jax

    out = {}
    for i, dev in enumerate(jax.devices()):
        stats = dev.memory_stats()
        if stats:
            out[f"{dev.platform}:{i}"] = {
                "bytesInUse": stats.get("bytes_in_use"),
                "bytesLimit": stats.get("bytes_limit"),
                "peakBytesInUse": stats.get("peak_bytes_in_use"),
            }
    return out


def device_memory_stats() -> dict:
    """Per-device HBM usage (the GOMEMLIMIT analog for device memory).

    Returns {} when the backend does not expose allocator stats (e.g.
    CPU mesh, or a remote-tunnel device). Unavailability is cached with
    a TTL (STATS_RETRY_S) so a polled status endpoint doesn't re-pay
    backend init every few seconds, yet one transient failure can't
    permanently blind the monitor."""
    global _stats_failed_at
    with _stats_lock:
        if (_stats_failed_at is not None
                and time.monotonic() - _stats_failed_at < STATS_RETRY_S):
            return {}
    try:
        out = _probe_device_stats()
    except Exception:
        out = {}
    with _stats_lock:
        _stats_failed_at = None if out else time.monotonic()
    return out
