"""Memory watchdog: gate allocations against host and device budgets.

Reference: usecases/memwatch/monitor.go:49 — CheckAlloc(:99) compares the
projected live heap against GOMEMLIMIT and rejects imports/cache growth
when it would overshoot. The TPU analog adds the HBM budget: device
arrays (vector stores, posting lists) are tracked against per-device HBM
capacity read from jax device memory_stats when available.
"""

from __future__ import annotations

import threading


class InsufficientMemoryError(MemoryError):
    pass


class MemoryMonitor:
    def __init__(self, host_limit_bytes: int | None = None,
                 device_limit_bytes: int | None = None,
                 max_utilization: float = 0.9):
        self.host_limit = host_limit_bytes
        self.device_limit = device_limit_bytes
        self.max_utilization = max_utilization
        self._lock = threading.Lock()
        # host-side tracked allocations (we can't read the Python live
        # heap cheaply; callers register their big buffers)
        self._tracked_host = 0

    # -- device -----------------------------------------------------------

    def device_budget(self) -> int | None:
        """Per-device HBM budget in bytes; explicit limit wins, else read
        from the backend (axon TPU exposes memory_stats)."""
        if self.device_limit is not None:
            return self.device_limit
        try:
            import jax

            stats = jax.devices()[0].memory_stats()
            if stats and "bytes_limit" in stats:
                return int(stats["bytes_limit"])
        except Exception:
            pass
        return None

    def device_in_use(self) -> int:
        try:
            import jax

            stats = jax.devices()[0].memory_stats()
            if stats and "bytes_in_use" in stats:
                return int(stats["bytes_in_use"])
        except Exception:
            pass
        return 0

    def check_device_alloc(self, nbytes: int) -> None:
        """Raise InsufficientMemoryError if landing ``nbytes`` more on the
        device would exceed the utilization cap (reference CheckAlloc
        semantics: refuse BEFORE allocating, don't OOM mid-import)."""
        budget = self.device_budget()
        if budget is None:
            return
        if self.device_in_use() + nbytes > budget * self.max_utilization:
            raise InsufficientMemoryError(
                f"device allocation of {nbytes} bytes would exceed "
                f"{self.max_utilization:.0%} of HBM budget {budget}")

    # -- host -------------------------------------------------------------

    def track_host(self, nbytes: int) -> None:
        with self._lock:
            self._tracked_host += nbytes

    def release_host(self, nbytes: int) -> None:
        with self._lock:
            self._tracked_host = max(0, self._tracked_host - nbytes)

    def check_host_alloc(self, nbytes: int) -> None:
        if self.host_limit is None:
            return
        with self._lock:
            projected = self._tracked_host + nbytes
        if projected > self.host_limit * self.max_utilization:
            raise InsufficientMemoryError(
                f"host allocation of {nbytes} bytes would exceed "
                f"{self.max_utilization:.0%} of limit {self.host_limit}")

    @property
    def tracked_host(self) -> int:
        return self._tracked_host


_DEVICE_STATS_UNAVAILABLE = False


def device_memory_stats() -> dict:
    """Per-device HBM usage (the GOMEMLIMIT analog for device memory).

    Returns {} when the backend does not expose allocator stats (e.g.
    CPU mesh, or a remote-tunnel device). Unavailability is cached so a
    polled status endpoint doesn't re-probe (the first probe may pay
    full JAX backend init)."""
    global _DEVICE_STATS_UNAVAILABLE
    if _DEVICE_STATS_UNAVAILABLE:
        return {}
    try:
        import jax

        out = {}
        for i, dev in enumerate(jax.devices()):
            stats = dev.memory_stats()
            if stats:
                out[f"{dev.platform}:{i}"] = {
                    "bytesInUse": stats.get("bytes_in_use"),
                    "bytesLimit": stats.get("bytes_limit"),
                    "peakBytesInUse": stats.get("peak_bytes_in_use"),
                }
        if not out:
            _DEVICE_STATS_UNAVAILABLE = True
        return out
    except Exception:
        _DEVICE_STATS_UNAVAILABLE = True
        return {}
