"""Cycle manager: periodic maintenance callbacks on daemon threads.

Reference: entities/cyclemanager/cyclemanager.go:34 — callbacks registered
with a ticker; tickers may back off exponentially while the callback
reports "nothing to do" and snap back to the base interval on activity.
Every callback runs panic-recovered (entities/errors GoWrapper): one
failing compaction must not kill the scheduler.

Used for: LSM flush+compaction (store_cyclecallbacks.go analog), vector
index compaction/reorganize cycles, tombstone cleanup.
"""

from __future__ import annotations

import logging
import threading
import time

logger = logging.getLogger(__name__)


class CycleCallback:
    """One periodic job. ``fn() -> bool`` returns True when it did work
    (resets the interval) and False when idle (backs off up to
    ``max_interval``)."""

    def __init__(self, name: str, fn, interval: float,
                 max_interval: float | None = None, backoff: float = 2.0):
        self.name = name
        self.fn = fn
        self.base_interval = interval
        self.max_interval = max_interval or interval * 8
        self.backoff = backoff
        self.current_interval = interval
        self.next_due = time.monotonic() + interval
        self.runs = 0
        self.failures = 0
        self.active = True

    def run(self) -> None:
        self.runs += 1
        try:
            did_work = self.fn()
        except Exception:
            self.failures += 1
            logger.exception("cycle callback %s failed", self.name)
            did_work = False
        if did_work:
            self.current_interval = self.base_interval
        else:
            self.current_interval = min(self.current_interval * self.backoff,
                                        self.max_interval)
        self.next_due = time.monotonic() + self.current_interval


class CycleManager:
    """Runs registered callbacks on a single scheduler thread.

    A single thread (not one per callback) keeps the background footprint
    flat no matter how many shards register compaction cycles — the
    reference bounds this with routine budgets per callback group.
    """

    def __init__(self):
        self._callbacks: dict[str, CycleCallback] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._pause_lock = threading.Lock()
        self._thread: threading.Thread | None = None

    def register(self, name: str, fn, interval: float,
                 max_interval: float | None = None) -> CycleCallback:
        cb = CycleCallback(name, fn, interval, max_interval)
        with self._lock:
            self._callbacks[name] = cb
        self._wake.set()
        return cb

    def unregister(self, name: str) -> None:
        with self._lock:
            self._callbacks.pop(name, None)

    def start(self) -> None:
        # under _lock: two concurrent start()s would otherwise both see a
        # dead handle and run two schedulers against the same buckets
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="cyclemanager")
            self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        # read the handle under _lock, join OUTSIDE it — the loop takes
        # _lock around every callback scan and could never exit otherwise
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                # a long compaction is still draining; keep the handle so a
                # subsequent start() can't spawn a second scheduler against
                # the same buckets
                logger.warning("cyclemanager did not stop within %.1fs", timeout)
            else:
                with self._lock:
                    if self._thread is t:
                        self._thread = None

    def trigger(self, name: str) -> None:
        """Force a callback to run at the next tick (tests, shutdown flush)."""
        with self._lock:
            cb = self._callbacks.get(name)
            if cb is not None:
                cb.next_due = 0.0
        self._wake.set()

    def run_now(self, name: str) -> bool:
        """Run a callback synchronously on the CALLING thread
        (deterministic tests and operational drives — e.g. forcing an
        ``epoch-maintenance`` pass without waiting a tick): takes the
        pause lock so it never overlaps the scheduler running the same
        callback, and feeds the same backoff bookkeeping. Returns False
        for unknown names."""
        with self._lock:
            cb = self._callbacks.get(name)
        if cb is None:
            return False
        with self._pause_lock:
            cb.run()
        return True

    def _loop(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                due = [cb for cb in self._callbacks.values()
                       if cb.active and cb.next_due <= now]
            for cb in due:
                if self._stop.is_set():
                    return
                with self._pause_lock:
                    cb.run()
            with self._lock:
                pending = [cb.next_due for cb in self._callbacks.values() if cb.active]
            wait = min(pending) - time.monotonic() if pending else 1.0
            if wait > 0:
                self._wake.wait(min(wait, 1.0))
                self._wake.clear()

    def pause(self):
        """Context manager: block callback execution for the duration
        (reference: Shard.BeginBackup pauses compaction and commit-log
        switching while backup files are streamed, shard_backup.go).
        An in-flight callback finishes first; new ones wait."""
        import contextlib

        @contextlib.contextmanager
        def _paused():
            with self._pause_lock:
                yield

        return _paused()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stats(self) -> dict:
        with self._lock:
            return {name: {"runs": cb.runs, "failures": cb.failures,
                           "interval": cb.current_interval}
                    for name, cb in self._callbacks.items()}
