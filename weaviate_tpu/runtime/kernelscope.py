"""Kernelscope — the device-time truth plane (four faces, ISSUE 17).

The zero-sync serving path deliberately removed the only honest device
clock we had: ``block_until_ready`` attribution exists only on sampled
traces, so tailboard's ``device`` phase was dispatch *wall*-clock. This
module turns the stamps the pipeline already takes for free into
attributed chip time, on every request:

1. **Per-dispatch chip timing without host sync.** The
   TransferPipeline's drain thread blocks on each handle's D2H anyway;
   the batcher stamps dispatch-submit before the device call and the
   drain thread stamps transfer-complete after ``handle.result()``.
   That window is ``device + memcpy``; subtracting the measured memcpy
   EWMA (fed by the sampled ``transfer.d2h`` split, which times
   ``block_until_ready`` separately from the ``np.asarray`` copy)
   yields device residency with **zero** new syncs. Attribution is
   labeled ``source="drain"``; when no async twin serves (sync engines,
   null-device bench stubs) it degrades to the dispatch wall window
   with ``source="wall"`` instead of crashing or emitting zeros.
   Residency feeds an EWMA + histogram per (index-kind, batch-bucket,
   k-bucket) compiled variant and tailboard's per-request ``device``
   phase.

2. **Per-query EXPLAIN.** ``?explain=true`` (REST) / ``x-explain``
   (gRPC metadata) installs a request-level sink; the batcher installs
   a dispatch-level sink around the engine call on its worker thread;
   engine layers call :func:`explain_note` with cheap host-side ints
   only (no device reads — graftlint G1 stays empty). The batcher adds
   its coalescing decision and merges the dispatch plan back into the
   request sink on the request thread. Explain never changes what is
   dispatched: sync and async answers are bit-identical.

3. **Per-tenant device metering.** Each dispatch's residency is
   apportioned across the requests it coalesced (weighted by rows
   scanned; a batcher is per-(shard, vector) so the owner labels are
   uniform) into ``weaviate_tpu_device_seconds_total{collection,
   tenant}`` — the interference signal the QoS scheduler consumes.

4. **On-demand kernel profiles.** :func:`capture_profile` wraps the
   already-wired ``jax.profiler`` programmatic trace, parses the
   perfetto/chrome events into per-kernel device-ms ranked by
   :data:`KERNEL_REGISTRY`, and persists the last K captures under the
   data dir (``GET /v1/debug/profile?ms=N``; ``benchkeeper --explain``
   attaches capture deltas to a regression verdict).
"""

from __future__ import annotations

import contextlib
import contextvars
import glob
import gzip
import json
import os
import tempfile
import threading
import time

from weaviate_tpu.runtime.metrics import (
    device_seconds_total,
    dispatch_device_seconds,
)

# -- face 1: drain-stamp device timing ----------------------------------------

#: EWMA weight for both the memcpy estimator and the per-variant
#: residency — heavy enough to track a recompile, light enough that one
#: preempted drain doesn't whipsaw the estimate.
_ALPHA = 0.2

_lock = threading.Lock()
# memcpy seconds per pow2-bytes bucket (bucket = nbytes.bit_length()),
# plus a global fallback for result shapes never seen on a sampled trace
_memcpy_ewma: dict[int, float] = {}
_memcpy_global: float | None = None
_memcpy_samples = 0
# (kind, b_bucket, k_bucket) -> {"ewma_ms", "last_ms", "n", "source"}
_variants: dict[tuple[str, int, int], dict] = {}
_meters: dict[tuple[str, str], float] = {}
_total_device_s = 0.0
_dispatches = {"drain": 0, "wall": 0}


def _bytes_bucket(nbytes: int) -> int:
    return int(nbytes).bit_length()


def observe_memcpy(seconds: float, nbytes: int) -> None:
    """Feed the memcpy estimator from a sampled ``transfer.d2h`` where
    device wait (``block_until_ready``) and the host copy were timed
    separately — the only place the split is directly measurable."""
    if seconds < 0 or nbytes < 0:
        return
    global _memcpy_global, _memcpy_samples
    bucket = _bytes_bucket(nbytes)
    with _lock:
        prev = _memcpy_ewma.get(bucket)
        _memcpy_ewma[bucket] = (seconds if prev is None
                                else _ALPHA * seconds + (1 - _ALPHA) * prev)
        _memcpy_global = (seconds if _memcpy_global is None
                          else _ALPHA * seconds
                          + (1 - _ALPHA) * _memcpy_global)
        _memcpy_samples += 1


def memcpy_estimate(nbytes: int) -> float:
    """Best-available memcpy seconds for a result of ``nbytes``: the
    pow2-bucket EWMA, else the global EWMA, else 0.0 (no sampled trace
    has run yet — the full drain window attributes to device, which is
    the pre-kernelscope behavior, never worse)."""
    with _lock:
        est = _memcpy_ewma.get(_bytes_bucket(nbytes))
        if est is None:
            est = _memcpy_global
    return 0.0 if est is None else est


def attribute(window_s: float, nbytes: int) -> tuple[float, float]:
    """Split a drain window (dispatch-submit .. transfer-complete) into
    ``(device_s, memcpy_s)``. The memcpy estimate is clamped into the
    window so both parts stay non-negative and sum to the window."""
    window_s = max(0.0, window_s)
    memcpy_s = min(max(0.0, memcpy_estimate(nbytes)), window_s)
    return window_s - memcpy_s, memcpy_s


def result_nbytes(value) -> int:
    """Total bytes of the numpy arrays in a transferred result pytree
    (tuple/list nesting); non-arrays contribute 0."""
    if value is None:
        return 0
    if isinstance(value, (tuple, list)):
        return sum(result_nbytes(v) for v in value)
    return int(getattr(value, "nbytes", 0) or 0)


def record_dispatch(kind: str, b_bucket: int, k_bucket: int,
                    device_s: float, source: str = "drain") -> None:
    """One dispatch's attributed device residency for the (index-kind,
    batch-bucket, k-bucket) compiled variant. ``source`` is ``drain``
    (drain-thread stamps minus memcpy EWMA) or ``wall`` (sync/null-
    device fallback: dispatch wall window)."""
    global _total_device_s
    device_s = max(0.0, device_s)
    key = (str(kind), int(b_bucket), int(k_bucket))
    with _lock:
        v = _variants.get(key)
        ms = device_s * 1000.0
        if v is None:
            _variants[key] = {"ewma_ms": ms, "last_ms": ms, "n": 1,
                              "source": source}
        else:
            v["ewma_ms"] = _ALPHA * ms + (1 - _ALPHA) * v["ewma_ms"]
            v["last_ms"] = ms
            v["n"] += 1
            v["source"] = source
        _total_device_s += device_s
        _dispatches[source] = _dispatches.get(source, 0) + 1
    try:
        dispatch_device_seconds.labels(
            key[0], str(key[1]), str(key[2]), source).observe(device_s)
    except Exception:
        pass


def apportion(device_s: float, weights: list[float]) -> list[float]:
    """Split one dispatch's residency across its coalesced requests,
    weighted (by rows scanned); degenerate weights split evenly. Shares
    sum exactly to ``device_s``."""
    n = len(weights)
    if n == 0:
        return []
    total = sum(w for w in weights if w > 0)
    if total <= 0:
        return [device_s / n] * n
    return [device_s * (max(w, 0.0) / total) for w in weights]


def meter(collection: str, tenant: str, device_s: float) -> None:
    """Accumulate attributed device seconds against a tenant — both the
    exported counter and an internal meter the accuracy check (sum of
    meters ~= total residency) reads back."""
    if device_s <= 0:
        return
    key = (str(collection or "-"), str(tenant or "-"))
    with _lock:
        _meters[key] = _meters.get(key, 0.0) + device_s
    try:
        device_seconds_total.labels(key[0], key[1]).inc(device_s)
    except Exception:
        pass


def total_device_seconds() -> float:
    with _lock:
        return _total_device_s


def meters_snapshot() -> dict[tuple[str, str], float]:
    with _lock:
        return dict(_meters)


# -- face 2: per-query EXPLAIN ------------------------------------------------

_explain_sink: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "kernelscope_explain_sink", default=None)


def explain_begin():
    """Install a fresh request-level explain sink on this thread;
    returns the reset token for :func:`explain_end`."""
    return _explain_sink.set({})


def explain_end(token) -> dict:
    plan = _explain_sink.get() or {}
    _explain_sink.reset(token)
    return plan


def explain_enabled() -> bool:
    return _explain_sink.get() is not None


@contextlib.contextmanager
def explain_scope(sink: dict):
    """Install ``sink`` as the ambient explain sink for the duration —
    how the batcher's worker thread captures engine notes for one
    dispatch without touching the request thread's sink."""
    token = _explain_sink.set(sink)
    try:
        yield sink
    finally:
        _explain_sink.reset(token)


def explain_note(section: str, **fields) -> None:
    """Record host-side plan facts under ``section`` in the ambient
    sink; a no-op (one contextvar read) when nobody asked to explain.
    Emission sites in ``engine/`` must pass plain host ints/strings —
    graftlint G5 pins that no device function feeds an argument."""
    sink = _explain_sink.get()
    if sink is None:
        return
    sec = sink.get(section)
    if sec is None:
        sink[section] = dict(fields)
    else:
        sec.update(fields)


def merge_plan(into: dict, plan: dict | None) -> None:
    """Fold a dispatch-level plan into a request-level sink, section by
    section (a multi-shard request keeps the last shard's engine
    sections; the batcher section is per-dispatch by construction)."""
    if not plan:
        return
    for section, fields in plan.items():
        if isinstance(fields, dict):
            cur = into.get(section)
            if isinstance(cur, dict):
                cur.update(fields)
            else:
                into[section] = dict(fields)
        else:
            into[section] = fields


def merge_into_request(plan: dict | None) -> None:
    sink = _explain_sink.get()
    if sink is None or not plan:
        return
    merge_plan(sink, plan)


# -- face 4: on-demand kernel profiles ----------------------------------------

#: friendly kernel name -> substrings matched (case-insensitive) against
#: trace event names. Mirrors the device programs the hot path compiles
#: (ops/pallas_kernels.py, ops/candidates.py, ops/topk.py, engine/ivf).
KERNEL_REGISTRY: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("fused_topk_scan", ("fused_topk",)),
    ("bq_scan_reduce", ("bq_scan", "bq_mxu", "bq_hamming")),
    ("pq4_scan_reduce", ("pq4_scan", "pq4_lut", "pq4_recon")),
    ("ivf_probe", ("ivf", "probe", "centroid")),
    ("gather_rescore_topk", ("gather_rescore", "shared_candidates",
                             "rescore")),
    ("merge_epoch_topk", ("merge_epoch", "merge_topk", "top_k", "topk")),
    ("distance_block", ("distance_block", "pairwise", "epoch_scan")),
)

_data_dir: str | None = None
_keep = 8
_capturer = None  # injectable trace capturer for tests (ms -> events)
_capture_seq = 0


def configure(data_dir: str | None = None, keep: int | None = None,
              capturer=None) -> None:
    """Server wiring: where captures persist (``<data_dir>/kernelscope``)
    and how many to keep. ``capturer`` overrides the jax.profiler-backed
    capture (tests inject synthetic trace events)."""
    global _data_dir, _keep, _capturer
    if data_dir is not None:
        _data_dir = str(data_dir)
    if keep is not None:
        _keep = max(1, int(keep))
    if capturer is not None:
        _capturer = capturer


def classify_kernel(event_name: str) -> str:
    low = str(event_name).lower()
    for friendly, pats in KERNEL_REGISTRY:
        if any(p in low for p in pats):
            return friendly
    return "other"


def summarize_trace_events(events) -> dict:
    """Aggregate chrome-trace complete events (``ph == "X"``, ``dur`` in
    microseconds) into per-kernel device-ms ranked descending, with the
    top raw event names kept per kernel for drill-down."""
    by_kernel: dict[str, dict] = {}
    for ev in events or ():
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        name = str(ev.get("name", ""))
        dur_ms = float(ev.get("dur", 0) or 0) / 1000.0
        if dur_ms <= 0:
            continue
        k = classify_kernel(name)
        agg = by_kernel.setdefault(
            k, {"kernel": k, "device_ms": 0.0, "events": 0, "names": {}})
        agg["device_ms"] += dur_ms
        agg["events"] += 1
        agg["names"][name] = agg["names"].get(name, 0.0) + dur_ms
    kernels = []
    for agg in by_kernel.values():
        top = sorted(agg.pop("names").items(), key=lambda kv: -kv[1])[:5]
        agg["device_ms"] = round(agg["device_ms"], 3)
        agg["top_events"] = [{"name": n, "device_ms": round(ms, 3)}
                             for n, ms in top]
        kernels.append(agg)
    kernels.sort(key=lambda a: -a["device_ms"])
    return {"kernels": kernels,
            "total_device_ms": round(sum(a["device_ms"] for a in kernels),
                                     3)}


def _jax_capture(ms: int):
    """Programmatic jax.profiler capture: trace for ``ms`` into a
    tempdir, then parse whatever perfetto/chrome trace the runtime
    wrote. Returns a list of chrome-trace events (possibly empty on a
    backend that only writes xplane protos)."""
    import jax

    events: list = []
    with tempfile.TemporaryDirectory(prefix="kernelscope-") as td:
        try:
            jax.profiler.start_trace(td, create_perfetto_trace=True)
        except TypeError:  # older signature without the kwarg
            jax.profiler.start_trace(td)
        try:
            time.sleep(max(0, int(ms)) / 1000.0)
        finally:
            jax.profiler.stop_trace()
        for path in glob.glob(os.path.join(td, "**", "*.json.gz"),
                              recursive=True) + glob.glob(
                os.path.join(td, "**", "*.trace.json"), recursive=True):
            try:
                if path.endswith(".gz"):
                    with gzip.open(path, "rt") as f:
                        doc = json.load(f)
                else:
                    with open(path) as f:
                        doc = json.load(f)
            except Exception:
                continue
            evs = doc.get("traceEvents") if isinstance(doc, dict) else doc
            if isinstance(evs, list):
                events.extend(e for e in evs if isinstance(e, dict))
    return events


def _capture_dir() -> str | None:
    if not _data_dir:
        return None
    d = os.path.join(_data_dir, "kernelscope")
    os.makedirs(d, exist_ok=True)
    return d


def capture_profile(ms: int, capturer=None) -> dict:
    """One on-demand profile: capture ``ms`` of device activity, rank it
    by kernel, persist the capture (pruning past the configured K)."""
    global _capture_seq
    cap = capturer or _capturer or _jax_capture
    t_wall = time.time()
    events = cap(int(ms))
    summary = summarize_trace_events(events)
    with _lock:
        _capture_seq += 1
        seq = _capture_seq
    record = {"id": f"cap-{int(t_wall)}-{seq}", "ms": int(ms),
              "captured_at": round(t_wall, 3),
              "raw_events": len(events or ()), **summary}
    d = _capture_dir()
    if d is not None:
        try:
            path = os.path.join(d, record["id"] + ".json")
            with open(path, "w") as f:
                json.dump(record, f, indent=1, sort_keys=True)
            kept = sorted(glob.glob(os.path.join(d, "cap-*.json")),
                          key=os.path.getmtime)
            for stale in kept[:-_keep]:
                try:
                    os.remove(stale)
                except OSError:
                    pass
        except Exception:
            pass  # persistence is best-effort; the capture still returns
    return record


def list_captures() -> list[dict]:
    """Persisted captures, newest first (summary fields only — the
    paramless ``/v1/debug/profile`` response; never triggers a trace)."""
    d = _capture_dir()
    if d is None:
        return []
    out = []
    for path in sorted(glob.glob(os.path.join(d, "cap-*.json")),
                       key=os.path.getmtime, reverse=True):
        try:
            with open(path) as f:
                rec = json.load(f)
        except Exception:
            continue
        out.append({"id": rec.get("id"), "ms": rec.get("ms"),
                    "captured_at": rec.get("captured_at"),
                    "total_device_ms": rec.get("total_device_ms"),
                    "kernels": [k.get("kernel")
                                for k in rec.get("kernels", ())]})
    return out


def load_capture(capture_id: str) -> dict | None:
    d = _capture_dir()
    if d is None:
        return None
    path = os.path.join(d, os.path.basename(str(capture_id)))
    if not path.endswith(".json"):
        path += ".json"
    try:
        with open(path) as f:
            return json.load(f)
    except Exception:
        return None


# -- snapshot / reset ---------------------------------------------------------

def snapshot() -> dict:
    """Kernelscope state for ``/v1/debug/kernelscope``: per-variant
    residency EWMAs, the memcpy estimator, per-tenant meters, totals.
    The debug route's description also documents the ``?explain=true``
    flag this module serves."""
    with _lock:
        variants = {f"{k[0]}/b{k[1]}/k{k[2]}":
                    {kk: (round(vv, 4) if isinstance(vv, float) else vv)
                     for kk, vv in v.items()}
                    for k, v in sorted(_variants.items())}
        memcpy = {"samples": _memcpy_samples,
                  "global_us": (None if _memcpy_global is None
                                else round(_memcpy_global * 1e6, 2)),
                  "buckets": {str(b): round(s * 1e6, 2)
                              for b, s in sorted(_memcpy_ewma.items())}}
        meters = {f"{c}/{t}": round(s, 6)
                  for (c, t), s in sorted(_meters.items())}
        total = _total_device_s
        disp = dict(_dispatches)
    return {"variants": variants, "memcpy": memcpy, "meters": meters,
            "total_device_seconds": round(total, 6),
            "dispatches": disp, "captures": len(list_captures())}


def reset_for_tests() -> None:
    """Drop all EWMA/meter/explain/capture state (conftest autouse —
    per-tenant meters leaking across tests would break the metering
    accuracy assertions)."""
    global _memcpy_global, _memcpy_samples, _total_device_s
    global _data_dir, _keep, _capturer, _capture_seq
    with _lock:
        _memcpy_ewma.clear()
        _memcpy_global = None
        _memcpy_samples = 0
        _variants.clear()
        _meters.clear()
        _total_device_s = 0.0
        _dispatches.clear()
        _dispatches.update({"drain": 0, "wall": 0})
        _capture_seq = 0
    _data_dir = None
    _keep = 8
    _capturer = None
