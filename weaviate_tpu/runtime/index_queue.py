"""Async vector-index queue.

Reference: adapters/repos/db/index_queue.go:42 — with ASYNC_INDEXING on,
imports enqueue vectors instead of mutating the vector index inline; a
shared worker pool drains batches into ``VectorIndex.AddBatch``, and a
bolt-backed checkpoint (indexcheckpoint/) tracks progress. Search is
eventually consistent with the queue (the reference searches both the
index and the queue's brute-force buffer; here the flat store IS
brute-force, so the only effect is indexing latency).

Crash story: vector indexes rebuild from the object store at shard open
(shard._restore_vector_indexes), so a lost queue never loses data — the
checkpoint only reports lag, matching the reference's recovery-by-replay.

Deletes racing queued inserts: delete(doc_id) tombstones the id inside
the queue so a drain never resurrects a deleted document (the ghost-row
hazard the reference guards with its own tombstone checks).
"""

from __future__ import annotations

import threading
from collections import deque

import numpy as np


class IndexQueue:
    def __init__(self, index, batch_size: int = 512,
                 start_worker: bool = True):
        self.index = index
        self.batch_size = batch_size
        self._lock = threading.Lock()
        self._pending: deque = deque()  # (doc_id, vector) pairs
        self._deleted: set[int] = set()
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._stop = threading.Event()
        self._flushed = 0  # vectors actually handed to the index
        # COUNT of popped-but-unapplied drain batches: drain() can run on
        # the worker AND a flush/stop caller concurrently, so a boolean
        # would let one finishing drain clear tombstones out from under
        # the other's in-flight batch
        self._in_flight = 0
        # the actual items of in-flight batches, still searchable via
        # snapshot() until the index visibly holds them
        self._in_flight_items: list = []
        self._thread = None
        if start_worker:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="index-queue")
            self._thread.start()

    # -- producer side -------------------------------------------------------

    def push(self, doc_ids, vectors) -> None:
        vectors = np.asarray(vectors, dtype=np.float32)
        with self._lock:
            for i, doc_id in enumerate(np.asarray(doc_ids).tolist()):
                self._pending.append((int(doc_id), vectors[i]))
            self._idle.clear()
        self._wake.set()

    def delete(self, doc_id: int) -> None:
        """Tombstone a doc id: a queued insert for it will be dropped at
        drain time (the index's own delete already ran). Recorded even
        while the queue LOOKS empty — a drain batch may be in flight, and
        the post-add re-check below needs the tombstone to undo a racing
        re-insert."""
        with self._lock:
            if self._pending or self._in_flight:
                self._deleted.add(int(doc_id))

    def size(self) -> int:
        with self._lock:
            return len(self._pending) + len(self._in_flight_items)

    def snapshot(self) -> list:
        """(doc_id, vector) pairs not yet visible in the index — pending
        plus the in-flight drain batch, minus tombstoned ids. Searches
        brute-force these so async indexing stays read-your-writes
        (reference: index queue search over unindexed vectors)."""
        with self._lock:
            dead = self._deleted
            return [(d, v) for d, v in
                    list(self._pending) + self._in_flight_items
                    if d not in dead]

    @property
    def flushed(self) -> int:
        return self._flushed

    # -- consumer side -------------------------------------------------------

    def drain(self) -> bool:
        """Drain everything queued right now (synchronous); True if any
        work was done. Also the cyclemanager-callback entry point."""
        did = False
        while self._drain_batch():
            did = True
        return did

    def _drain_batch(self) -> bool:
        with self._lock:
            if not self._pending:
                if not self._in_flight:
                    self._deleted.clear()
                    self._idle.set()
                return False
            batch = [self._pending.popleft()
                     for _ in range(min(self.batch_size,
                                        len(self._pending)))]
            dead = set(self._deleted)
            self._in_flight += 1
            self._in_flight_items.extend(batch)
        applied = False
        try:
            live = [(d, v) for d, v in batch if d not in dead]
            if live:
                ids = np.asarray([d for d, _ in live], dtype=np.int64)
                vecs = np.stack([v for _, v in live])
                self.index.add_batch(ids, vecs)
            applied = True
            with self._lock:
                self._flushed += len(live)
            # a delete may have raced the add_batch above: its idx.delete
            # found nothing (vector not added yet) and our `dead` snapshot
            # predates it — undo the resurrect now
            with self._lock:
                raced = [d for d, _ in live if d in self._deleted]
            for d in raced:
                self.index.delete(d)
        finally:
            with self._lock:
                self._in_flight -= 1
                batch_ids = {d for d, _ in batch}
                self._in_flight_items = [
                    (d, v) for d, v in self._in_flight_items
                    if d not in batch_ids]
                if not applied:
                    # add_batch failed (device OOM etc.): the batch was
                    # already popped — requeue it or the acknowledged
                    # vectors silently vanish from index AND snapshot
                    self._pending.extendleft(reversed(batch))
                    self._idle.clear()
                if not self._pending and not self._in_flight:
                    self._deleted.clear()
                    self._idle.set()
        # on add_batch failure the exception propagates (ending this drain
        # round — no hot retry loop); the worker's next wake tick retries
        # the requeued batch
        return True

    def wait_idle(self, timeout: float | None = None) -> bool:
        """Block until the queue is fully drained (flush/close path)."""
        self._wake.set()
        return self._idle.wait(timeout)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(0.2)
            self._wake.clear()
            try:
                self.drain()
            except Exception:  # keep the worker alive; next push retries
                import logging

                logging.getLogger(__name__).exception(
                    "index queue drain failed")

    def stop(self, flush: bool = True, timeout: float = 10.0) -> None:
        if flush:
            self.drain()
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout)
