"""Persistent XLA compilation-cache setup, shared by the server entry
point and the offline tools (bulk builds, benchmarks).

The vector store's pow2 capacity ladder and the bulk-build link pipeline
re-jit per shape level; each program costs 0.5-20 s to compile (more on a
remote-compile rig). Two defaults make every process after the first
start warm:

- cache dir in the USER cache location (keys are program + hardware, not
  instance state), overridable via JAX_COMPILATION_CACHE_DIR
- persistence threshold 0: jax's default skips sub-1 s compiles, which
  is exactly the population the capacity ladder is made of
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_done = False


def ensure_compile_cache() -> None:
    """Idempotent; call before the first jit dispatch."""
    global _done
    if _done:
        return
    _done = True
    try:
        import jax

        explicit = bool(os.environ.get("JAX_COMPILATION_CACHE_DIR"))
        if not explicit and jax.default_backend() == "cpu":
            # CPU-platform AOT executables embed the COMPILING machine's
            # feature set; on rigs where compiles are serviced remotely
            # the cached artifact can then be loaded on a host missing
            # those features (observed: +amx entries from the compile
            # service loaded on a non-amx host — a SIGILL hazard). CPU
            # compiles are cheap locally; cache only accelerator
            # programs unless the user opts in with an explicit dir.
            return
        if not explicit:
            cache_root = os.environ.get("XDG_CACHE_HOME") or \
                os.path.join(os.path.expanduser("~"), ".cache")
            cache_dir = os.path.join(cache_root, "weaviate-tpu",
                                     "xla-cache")
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:  # noqa: BLE001 — cache is best-effort
        logger.warning("compilation cache disabled: %s", e)
