"""Persistent XLA compilation-cache setup, shared by the server entry
point and the offline tools (bulk builds, benchmarks).

The vector store's pow2 capacity ladder and the bulk-build link pipeline
re-jit per shape level; each program costs 0.5-20 s to compile (more on a
remote-compile rig). Two defaults make every process after the first
start warm:

- cache dir in the USER cache location (keys are program + hardware, not
  instance state), overridable via JAX_COMPILATION_CACHE_DIR
- persistence threshold 0: jax's default skips sub-1 s compiles, which
  is exactly the population the capacity ladder is made of
"""

from __future__ import annotations

import logging
import os
import threading

logger = logging.getLogger(__name__)

_done = False
_metrics_installed = False

# executable-footprint estimate: XLA keeps compiled programs resident in
# HBM but exposes no per-executable size; each backend compile bumps one
# ledger entry by a flat estimate (HBM_EXECUTABLE_ESTIMATE_BYTES,
# default 4 MiB) so the allocator-vs-ledger delta in /v1/debug/memory
# isn't silently dominated by executables. Explicitly labeled
# sharding="estimate" — this is a planning number, not an exact count.
_exec_key: int | None = None
_exec_count = 0
_exec_lock = threading.Lock()


def _note_executable() -> None:
    """Called from jax's monitoring callbacks, which fire on whatever
    thread finished the compile — the lock keeps concurrent first
    compiles from double-registering (and orphaning) ledger entries."""
    global _exec_key, _exec_count
    try:
        from weaviate_tpu.runtime.hbm_ledger import ledger

        est_each = int(os.environ.get("HBM_EXECUTABLE_ESTIMATE_BYTES",
                                      str(4 << 20)))
        with _exec_lock:
            _exec_count += 1
            if _exec_key is None:
                _exec_key = ledger.register(
                    "executables", est_each * _exec_count,
                    collection="_runtime", shard="-", tenant="",
                    sharding="estimate")
            else:
                ledger.update(_exec_key, est_each * _exec_count)
    except Exception:  # noqa: BLE001 — accounting is best-effort
        pass


def install_compile_metrics() -> None:
    """Feed compile-time histograms and cache hit/miss counters from
    jax's monitoring stream (idempotent; safe without jax).

    jax emits ``record_event_duration_secs`` for every backend compile
    ('/jax/core/compile/backend_compile_duration' and friends) and
    ``record_event`` for persistent-cache outcomes ('/jax/compilation_
    cache/cache_hits' | 'cache_misses' | 'task_disabled_cache'). The
    event key IS the signature label — keys are a small fixed set, so
    cardinality stays bounded while still splitting tracing/lowering/
    backend-compile time."""
    global _metrics_installed
    if _metrics_installed:
        return
    _metrics_installed = True
    try:
        from jax import monitoring

        from weaviate_tpu.runtime.metrics import (compile_cache_events,
                                                  jit_compile_duration)

        def _on_duration(event: str, duration: float, **kw) -> None:
            if "compile" in event:
                jit_compile_duration.labels(event).observe(duration)
                if "backend_compile" in event:
                    _note_executable()

        def _on_event(event: str, **kw) -> None:
            if "cache_hit" in event:
                compile_cache_events.labels("hit").inc()
            elif "cache_miss" in event:
                compile_cache_events.labels("miss").inc()
            elif "compilation_cache" in event:
                compile_cache_events.labels("other").inc()

        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
    except Exception as e:  # noqa: BLE001 — metrics are best-effort
        logger.debug("compile metrics unavailable: %s", e)


def ensure_compile_cache() -> None:
    """Idempotent; call before the first jit dispatch."""
    global _done
    if _done:
        return
    _done = True
    install_compile_metrics()
    try:
        import jax

        explicit = bool(os.environ.get("JAX_COMPILATION_CACHE_DIR"))
        if not explicit and jax.default_backend() == "cpu":
            # CPU-platform AOT executables embed the COMPILING machine's
            # feature set; on rigs where compiles are serviced remotely
            # the cached artifact can then be loaded on a host missing
            # those features (observed: +amx entries from the compile
            # service loaded on a non-amx host — a SIGILL hazard). CPU
            # compiles are cheap locally; cache only accelerator
            # programs unless the user opts in with an explicit dir.
            return
        if not explicit:
            cache_root = os.environ.get("XDG_CACHE_HOME") or \
                os.path.join(os.path.expanduser("~"), ".cache")
            cache_dir = os.path.join(cache_root, "weaviate-tpu",
                                     "xla-cache")
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:  # noqa: BLE001 — cache is best-effort
        logger.warning("compilation cache disabled: %s", e)
