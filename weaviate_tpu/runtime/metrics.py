"""Prometheus-style metrics registry with text exposition.

Reference: usecases/monitoring/prometheus.go:28 (~70 metric vecs: LSM,
vector index, backup, queries) served on PROMETHEUS_MONITORING_PORT.
Hand-rolled (no prometheus_client in the image): Counter/Gauge/Histogram
with label vectors and the /metrics text format.
"""

from __future__ import annotations

import os
import threading
import time
from bisect import bisect_left

# -- registry-level label-cardinality guard (ISSUE 15 satellite) --------------
#
# An adversarial (or just unbounded) tenant/collection stream must not be
# able to grow the exposition without bound: past the per-metric series
# cap, NEW label tuples collapse into one reserved all-``other`` series
# and the redirect is counted in
# ``weaviate_tpu_metric_series_dropped_total{metric}``. Existing series
# keep updating — the cap bounds growth, it never forgets live series.

_SERIES_CAP: int | None = None  # lazy env read (None = unread)


def _series_cap() -> int:
    global _SERIES_CAP
    if _SERIES_CAP is None:
        try:
            _SERIES_CAP = int(os.environ.get(
                "WEAVIATE_TPU_METRIC_MAX_SERIES", "2000"))
        except ValueError:
            _SERIES_CAP = 2000
    return _SERIES_CAP


def reset_series_cap_for_tests() -> None:
    """Re-read WEAVIATE_TPU_METRIC_MAX_SERIES on next use."""
    global _SERIES_CAP
    _SERIES_CAP = None


def _count_series_dropped(metric_name: str) -> None:
    try:
        metric_series_dropped.labels(metric_name).inc()
    except Exception:  # registration order — must never fail callers
        pass


def escape_label_value(v) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote and newline must be escaped or a value like ``a"b`` corrupts
    the whole scrape (text format spec, "Escaping")."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(s: str) -> str:
    """HELP lines escape backslash and newline (not quotes)."""
    return str(s).replace("\\", "\\\\").replace("\n", "\\n")


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str = "", label_names: tuple = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}
        # reserved overflow series for the cardinality guard (the guard
        # itself is exempt — its one label is metric names, bounded)
        self._overflow = tuple("other" for _ in self.label_names)
        self._guarded = bool(self.label_names) and \
            name != "weaviate_tpu_metric_series_dropped_total"
        # per-metric cap override (None = the registry-wide env cap):
        # a metric whose label budget is deliberately larger than the
        # generic default (the tailboard phase histogram) sets this
        self.max_series: int | None = None

    def labels(self, *values, **kw):
        if kw:
            values = tuple(kw.get(n, "") for n in self.label_names)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(f"{self.name}: expected labels {self.label_names}")
        dropped = False
        with self._lock:
            child = self._children.get(values)
            if child is None:
                cap = (self.max_series if self.max_series is not None
                       else _series_cap())
                if (self._guarded and values != self._overflow
                        and len(self._children) >= cap):
                    # cardinality guard: redirect the NEW tuple into the
                    # reserved all-"other" series instead of growing
                    dropped = True
                    values = self._overflow
                    child = self._children.get(values)
                if child is None:
                    child = self._new_child()
                    self._children[values] = child
        if dropped:
            _count_series_dropped(self.name)
        return child

    def _default(self):
        if self.label_names:
            raise ValueError(f"{self.name} has labels; use .labels(...)")
        return self.labels()

    def remove(self, *values) -> None:
        """Drop one label child (a deleted collection/shard must not keep
        exporting a stale 0-valued series forever)."""
        values = tuple(str(v) for v in values)
        with self._lock:
            self._children.pop(values, None)

    def _label_str(self, values: tuple) -> str:
        if not values:
            return ""
        pairs = ",".join(f'{n}="{escape_label_value(v)}"'
                         for n, v in zip(self.label_names, values))
        return "{" + pairs + "}"


class _CounterChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0):
        self._default().inc(amount)

    def expose(self, openmetrics: bool = False) -> list[str]:
        # OpenMetrics names the FAMILY without the reserved _total
        # suffix while samples keep it — a strict OM parser (real
        # Prometheus negotiating openmetrics-text) rejects a family
        # ending in _total; 0.0.4 text keeps the historical full name
        family = self.name
        if openmetrics and family.endswith("_total"):
            family = family[: -len("_total")]
        out = [f"# HELP {family} {_escape_help(self.help)}",
               f"# TYPE {family} counter"]
        with self._lock:  # labels() inserts race the scrape iteration
            children = sorted(self._children.items())
        for lv, child in children:
            out.append(f"{self.name}{self._label_str(lv)} {child.value}")
        return out


class _GaugeChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float):
        with self._lock:
            self.value = v

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)


class Gauge(_Metric):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, v: float):
        self._default().set(v)

    def inc(self, amount: float = 1.0):
        self._default().inc(amount)

    def dec(self, amount: float = 1.0):
        self._default().dec(amount)

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} gauge"]
        with self._lock:
            children = sorted(self._children.items())
        for lv, child in children:
            out.append(f"{self.name}{self._label_str(lv)} {child.value}")
        return out


DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


class _HistogramChild:
    """Observations land in ONE slot (their lowest bucket, found by
    bisect) and cumulate lazily at expose time — O(log buckets) on the
    hot path instead of a linear walk under the lock. The always-on
    request-phase histograms (tailboard) observe on every served
    request, so this is serving-path code, not just scrape plumbing."""

    __slots__ = ("buckets", "slot_counts", "total", "count", "exemplars",
                 "_lock")

    def __init__(self, buckets):
        self.buckets = buckets
        # slot_counts[i]: observations whose LOWEST bucket is i;
        # index len(buckets) = fell past every bound (+Inf only)
        self.slot_counts = [0] * (len(buckets) + 1)
        self.total = 0.0
        self.count = 0
        # per-bucket last exemplar (index len(buckets) = +Inf), lazily
        # allocated — most histograms never carry one
        self.exemplars: list | None = None
        self._lock = threading.Lock()

    def observe(self, v: float, exemplar: dict | None = None):
        """``exemplar``: OpenMetrics exemplar labels (e.g.
        ``{"trace_id": ...}``) attached to the lowest bucket ``v`` falls
        in (and +Inf) — how a phase-histogram bucket links to a
        tail-retained trace."""
        # v <= buckets[idx] for the first idx with buckets[idx] >= v
        idx = bisect_left(self.buckets, v)
        with self._lock:
            self.total += v
            self.count += 1
            self.slot_counts[idx] += 1
            if exemplar is not None:
                if self.exemplars is None:
                    self.exemplars = [None] * (len(self.buckets) + 1)
                ex = (dict(exemplar), float(v), time.time())
                self.exemplars[min(idx, len(self.buckets))] = ex
                self.exemplars[len(self.buckets)] = ex

    def cumulative_counts(self) -> list[int]:
        """Per-``le`` cumulative counts (the exposition's bucket lines).
        Caller need not hold the lock; a racing observe skews one scrape
        by one observation at worst."""
        out = []
        running = 0
        for c in self.slot_counts[:-1]:
            running += c
            out.append(running)
        return out

    def time(self):
        return _Timer(self)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_text="", label_names=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_text, label_names)
        self.buckets = tuple(buckets)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float, exemplar: dict | None = None):
        self._default().observe(v, exemplar=exemplar)

    def time(self):
        """Context manager observing elapsed seconds."""
        return _Timer(self._default())

    @staticmethod
    def _exemplar_str(ex) -> str:
        """OpenMetrics exemplar rendering: `` # {labels} value ts`` —
        label values pass the same escaping as ordinary labels (a
        trace id is opaque input; an embedded quote must not corrupt
        the scrape)."""
        labels, value, ts = ex
        pairs = ",".join(f'{k}="{escape_label_value(v)}"'
                         for k, v in labels.items())
        return f" # {{{pairs}}} {value} {round(ts, 3)}"

    def expose(self, openmetrics: bool = False) -> list[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            children = sorted(self._children.items())
        for lv, child in children:
            base = self._label_str(lv)[1:-1] if lv else ""
            exemplars = child.exemplars if openmetrics else None
            for i, (b, c) in enumerate(zip(self.buckets,
                                           child.cumulative_counts())):
                lbl = f'{{{base}{"," if base else ""}le="{b}"}}'
                line = f"{self.name}_bucket{lbl} {c}"
                if exemplars is not None and exemplars[i] is not None:
                    line += self._exemplar_str(exemplars[i])
                out.append(line)
            lbl_inf = f'{{{base}{"," if base else ""}le="+Inf"}}'
            line = f"{self.name}_bucket{lbl_inf} {child.count}"
            if exemplars is not None and exemplars[-1] is not None:
                line += self._exemplar_str(exemplars[-1])
            out.append(line)
            suffix = "{" + base + "}" if base else ""
            out.append(f"{self.name}_sum{suffix} {child.total}")
            out.append(f"{self.name}_count{suffix} {child.count}")
        return out


class _Timer:
    def __init__(self, child):
        self._child = child

    def __enter__(self):
        import time

        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time

        self._child.observe(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _register(self, cls, name, help_text, label_names, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(f"metric {name} already registered as "
                                     f"{existing.kind}")
                return existing
            m = cls(name, help_text, label_names, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help_text="", label_names=()) -> Counter:
        return self._register(Counter, name, help_text, label_names)

    def gauge(self, name, help_text="", label_names=()) -> Gauge:
        return self._register(Gauge, name, help_text, label_names)

    def histogram(self, name, help_text="", label_names=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help_text, label_names,
                              buckets=buckets)

    def expose(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition format. ``openmetrics=True`` emits
        the OpenMetrics flavor: histogram buckets carry their exemplars
        and the stream ends with ``# EOF`` — what a client negotiating
        ``Accept: application/openmetrics-text`` receives."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, (Histogram, Counter)):
                lines.extend(m.expose(openmetrics=openmetrics))
            else:
                lines.extend(m.expose())
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"


#: process-wide default registry (reference: one prometheus registry per node)
registry = MetricsRegistry()

# -- the standard metric set (subset of prometheus.go's ~70 vecs) -------------

query_duration = registry.histogram(
    "weaviate_tpu_query_duration_seconds",
    "Query latency by collection and query type",
    ("collection", "query_type"))
objects_total = registry.counter(
    "weaviate_tpu_objects_total",
    "Object mutations by collection and operation",
    ("collection", "operation"))
vector_index_size = registry.gauge(
    "weaviate_tpu_vector_index_size",
    "Live vectors per collection/shard", ("collection", "shard"))
vector_index_operations = registry.counter(
    "weaviate_tpu_vector_index_operations_total",
    "Vector index ops", ("collection", "operation"))
lsm_segment_count = registry.gauge(
    "weaviate_tpu_lsm_segment_count",
    "Segments per bucket", ("bucket",))

# -- LSM internals (reference: lsmkv/metrics.go) ------------------------------

lsm_wal_bytes = registry.counter(
    "weaviate_tpu_lsm_wal_bytes_total",
    "WAL bytes appended per bucket", ("bucket",))
lsm_memtable_bytes = registry.gauge(
    "weaviate_tpu_lsm_memtable_bytes",
    "Active memtable size estimate per bucket", ("bucket",))
lsm_flush_duration = registry.histogram(
    "weaviate_tpu_lsm_flush_duration_seconds",
    "Sealed-memtable to segment flush latency", ("bucket",))
lsm_compaction_duration = registry.histogram(
    "weaviate_tpu_lsm_compaction_duration_seconds",
    "Segment compaction latency", ("bucket",))

# -- crash recovery (storage/recovery.py records these at every bucket
#    open; /v1/debug/storage serves the same registry as JSON) ----------------

recovery_frames_replayed = registry.counter(
    "weaviate_tpu_recovery_frames_replayed_total",
    "Intact WAL frames re-applied into the memtable at bucket open",
    ("bucket",))
recovery_bytes_truncated = registry.counter(
    "weaviate_tpu_recovery_bytes_truncated_total",
    "Torn-tail WAL bytes dropped at bucket open (crash mid-append)",
    ("bucket",))
recovery_wals_quarantined = registry.counter(
    "weaviate_tpu_recovery_wals_quarantined_total",
    "WAL files renamed .corrupt at open: a frame failed its CRC with "
    "intact bytes after it (mid-file corruption, not a torn tail)",
    ("bucket",))
recovery_segments_quarantined = registry.counter(
    "weaviate_tpu_recovery_segments_quarantined_total",
    "Segment files renamed .corrupt at open (unparseable header/"
    "footer/index)", ("bucket",))
recovery_segments_recovered = registry.counter(
    "weaviate_tpu_recovery_segments_recovered_total",
    "Segments written from replayed WAL state at bucket open",
    ("bucket",))

# -- vector index internals (reference: hnsw/metrics.go) ----------------------

vector_index_tombstones = registry.gauge(
    "weaviate_tpu_vector_index_tombstones",
    "Tombstoned (deleted, unreclaimed) vectors",
    ("collection", "shard", "vector"))
vector_index_hbm_bytes = registry.gauge(
    "weaviate_tpu_vector_index_hbm_bytes",
    "Device memory held by the index's arrays",
    ("collection", "shard", "vector"))
vector_index_compressed = registry.gauge(
    "weaviate_tpu_vector_index_compressed",
    "1 when the index serves from quantized codes",
    ("collection", "shard", "vector"))

# -- replication (reference: replication metrics in monitoring/) --------------

replication_phase_total = registry.counter(
    "weaviate_tpu_replication_phase_total",
    "2PC phases by outcome", ("phase", "status"))
hashbeat_repairs_total = registry.counter(
    "weaviate_tpu_hashbeat_objects_repaired_total",
    "Objects propagated by Merkle anti-entropy", ("direction",))
replication_staged_expired = registry.counter(
    "weaviate_tpu_replication_staged_expired_total",
    "Staged 2PC entries dropped or refused past the staged-entry TTL "
    "(orphaned prepares whose coordinator never came back, and late "
    "commits racing a partition heal)", ("collection", "shard"))
hashbeat_rounds = registry.counter(
    "weaviate_tpu_hashbeat_rounds_total",
    "Anti-entropy rounds run per locally-owned shard (one round = one "
    "Merkle walk against every peer replica)", ("collection", "shard"))
replica_divergent_entries = registry.gauge(
    "weaviate_tpu_replica_divergent_entries",
    "Divergence estimate from the last anti-entropy round: entries "
    "whose digests disagreed with at least one peer replica (0 once "
    "the replicas converged)", ("collection", "shard"))

# -- dynamic query batching ---------------------------------------------------

batcher_batch_size = registry.histogram(
    "weaviate_tpu_query_batcher_batch_size",
    "Queries coalesced per device dispatch", (),
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
batcher_wait_duration = registry.histogram(
    "weaviate_tpu_query_batcher_wait_seconds",
    "Time a query waits in the batcher queue before its dispatch starts")
batcher_execute_duration = registry.histogram(
    "weaviate_tpu_query_batcher_execute_seconds",
    "Device dispatch+materialize time of the coalesced batch a query "
    "rode in")
batcher_filtered_batched = registry.counter(
    "weaviate_tpu_query_batcher_filtered_batched_total",
    "Filtered requests served inside a coalesced bitmask-batched "
    "dispatch (instead of a solo device program)")
batcher_compile_bucket = registry.counter(
    "weaviate_tpu_query_batcher_compile_bucket_total",
    "Coalesced dispatches by padded pow2 (batch, k) bucket — the bucket "
    "set bounds the number of compiled program variants", ("b", "k"))
batcher_async_dispatched = registry.counter(
    "weaviate_tpu_query_batcher_async_dispatched_total",
    "Coalesced drains dispatched through the zero-sync pipeline: "
    "results stay device-resident and drain D2H on the transfer thread")
batcher_overlapped = registry.counter(
    "weaviate_tpu_query_batcher_overlapped_total",
    "Dispatches launched while a previous batch was still draining "
    "D2H — the overlap the double-buffered pipeline exists for")
batcher_transfer_duration = registry.histogram(
    "weaviate_tpu_query_batcher_transfer_seconds",
    "D2H drain time (transfer.d2h window) of the coalesced batch a "
    "query rode in, overlapped with the next dispatch")
batcher_hybrid_batched = registry.counter(
    "weaviate_tpu_query_batcher_hybrid_batched_total",
    "Hybrid (sparse+dense) requests served inside a coalesced device "
    "dispatch — sparse operands rode the drain the way allow_bits do")

# -- inverted index (text/inverted.py) ----------------------------------------

postings_cache_hits = registry.counter(
    "weaviate_tpu_postings_cache_hits_total",
    "Posting-list reads served from the per-shard LRU postings cache")
postings_cache_misses = registry.counter(
    "weaviate_tpu_postings_cache_misses_total",
    "Posting-list reads that went to the LSM searchable bucket — the "
    "host-side cost floor of BM25 planning and the hybridplane's "
    "posting pack")

# -- epoch store (engine/epochs.py publishes on seal/compact/drop;
#    db/collection.py bumps the migration counter) ----------------------------

epoch_count = registry.gauge(
    "weaviate_tpu_epoch_count",
    "Device epochs in the stack (sealed + active) per epoch-backed "
    "vector store", ("collection", "shard"))
epoch_live_rows = registry.gauge(
    "weaviate_tpu_epoch_live_rows",
    "Live (non-tombstoned) rows per device epoch; series are removed "
    "when their epoch compacts away or migrates",
    ("collection", "shard", "epoch"))
epoch_tombstone_rows = registry.gauge(
    "weaviate_tpu_epoch_tombstone_rows",
    "Tombstoned rows per device epoch — what the background compaction "
    "policy folds out to reclaim HBM",
    ("collection", "shard", "epoch"))
epoch_compactions = registry.counter(
    "weaviate_tpu_epoch_compactions_total",
    "Sealed epochs folded on device (live rows repacked, tombstoned "
    "HBM released through the ledger finalizers)",
    ("collection", "shard"))
epoch_migrations = registry.counter(
    "weaviate_tpu_epoch_migrations_total",
    "Sealed epochs migrated to a sibling shard with headroom instead "
    "of latching 507 rejections at the HBM watermark",
    ("collection", "shard"))

# -- HBM ledger (runtime/hbm_ledger.py keeps these current on every
#    register/update/release; memwatch sets the budget + pressure) ------------

hbm_bytes = registry.gauge(
    "weaviate_tpu_hbm_bytes",
    "Live device bytes registered in the HBM ledger",
    ("collection", "shard", "component"))
hbm_peak_bytes = registry.gauge(
    "weaviate_tpu_hbm_peak_bytes",
    "High-water mark of ledger-registered device bytes since process "
    "start")
hbm_host_bytes = registry.gauge(
    "weaviate_tpu_hbm_host_bytes",
    "Ledger device bytes attributed per mesh host (hierarchical "
    "ICI+DCN sharding); host values sum exactly to the ledger's live "
    "device total",
    ("host",))
hbm_budget_bytes = registry.gauge(
    "weaviate_tpu_hbm_budget_bytes",
    "Per-device HBM budget admission control gates against (0 = no "
    "budget known)")
memory_pressure_total = registry.counter(
    "weaviate_tpu_memory_pressure_total",
    "Admission-control memory-pressure events",
    ("resource", "action"))

# -- faultline / unified failure policy (runtime/faultline.py,
#    runtime/retry.py, runtime/degrade.py, cluster/transport.py) --------------

fault_injected_total = registry.counter(
    "weaviate_tpu_fault_injected_total",
    "Faults executed by an armed faultline schedule, by fault point "
    "and action — a chaos run asserts this accounts for every "
    "scheduled injection", ("point", "action"))
retries_total = registry.counter(
    "weaviate_tpu_retries_total",
    "RetryPolicy attempt outcomes by operation: retried (backoff "
    "taken), recovered (a retry succeeded), exhausted (attempts used "
    "up), deadline (budget could not absorb another attempt)",
    ("op", "outcome"))
deadline_exceeded_total = registry.counter(
    "weaviate_tpu_deadline_exceeded_total",
    "Requests that ran out of their propagated time budget, by the "
    "layer that noticed", ("layer",))
circuit_state = registry.gauge(
    "weaviate_tpu_circuit_state",
    "Per-peer transport circuit breaker state: 0=closed, 1=half-open, "
    "2=open", ("peer",))
circuit_transitions_total = registry.counter(
    "weaviate_tpu_circuit_transitions_total",
    "Circuit breaker state transitions by peer and target state",
    ("peer", "to"))
degraded_results_total = registry.counter(
    "weaviate_tpu_degraded_results_total",
    "Requests answered with explicitly-marked PARTIAL results instead "
    "of an error (dead replica skipped, consistency level downgraded)",
    ("kind", "collection"))
component_unhealthy = registry.gauge(
    "weaviate_tpu_component_unhealthy",
    "1 while a serving component (query batcher, native data plane) is "
    "flagged unhealthy after a dispatch failure; cleared on recovery",
    ("component",))
batcher_dispatch_retries = registry.counter(
    "weaviate_tpu_query_batcher_dispatch_retries_total",
    "Coalesced device dispatches retried once after a failure before "
    "erroring their own waiters")
native_dispatch_retries = registry.counter(
    "weaviate_tpu_native_plane_dispatch_retries_total",
    "Native data-plane pipelined batches retried once through the sync "
    "path after a device/transfer fault")

# -- tracing (runtime/tracing.py feeds this on every finished span) -----------

span_duration = registry.histogram(
    "weaviate_tpu_span_duration_seconds",
    "Trace span durations by span name", ("span",))

# -- tailboard: always-on latency attribution (runtime/tailboard.py) ----------

request_phase_seconds = registry.histogram(
    "weaviate_tpu_request_phase_seconds",
    "Always-on per-request latency attribution from monotonic edge/"
    "batcher/transfer stamps (no device sync on unsampled paths): phase "
    "is queue_wait (batcher queue), device (kernelscope-attributed chip "
    "residency: drain window minus the memcpy EWMA, source=drain; wall "
    "window on sync paths), transfer (memcpy share of the D2H drain) or "
    "host (everything else); tenant and "
    "collection pass the top-K cardinality guard (overflow: other). "
    "Buckets carry OpenMetrics exemplars naming tail-retained trace ids",
    ("operation", "phase", "collection", "tenant"),
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5, 5.0, 10.0))
# the phase histogram's own series budget must dominate the generic
# per-metric cap: its label space is operations x 4 phases x the
# tailboard top-K guards (64 collections, 32 tenants) — a modest
# multi-tenant deployment legitimately exceeds the 2000 default, and
# collapsing the headline attribution labels to "other" would defeat
# the metric's purpose while the guards already bound it
try:
    request_phase_seconds.max_series = int(os.environ.get(
        "WEAVIATE_TPU_PHASE_MAX_SERIES", "16000") or 16000)
except ValueError:
    request_phase_seconds.max_series = 16000
tail_retained_total = registry.counter(
    "weaviate_tpu_tail_retained_total",
    "Traces kept by the tail-based retention decision at request "
    "completion (always kept regardless of TRACE_SAMPLE_RATE), by "
    "reason: slow, error, deadline, degraded, fault",
    ("reason",))
slo_burn_rate = registry.gauge(
    "weaviate_tpu_slo_burn_rate",
    "Error-budget burn rate per SLO objective and sliding window "
    "(bad-fraction / (1 - objective)): 1.0 burns exactly the budget, "
    "14.4x on the fast window is the classic page threshold; refreshed "
    "at scrape and by /v1/debug/slo",
    ("slo", "window"))
metric_series_dropped = registry.counter(
    "weaviate_tpu_metric_series_dropped_total",
    "Label-set lookups redirected into the reserved 'other' overflow "
    "series by the per-metric cardinality cap "
    "(WEAVIATE_TPU_METRIC_MAX_SERIES) — nonzero means some stream of "
    "label values (tenants, collections) outgrew the exposition budget",
    ("metric",))
flight_snapshots_total = registry.counter(
    "weaviate_tpu_flight_snapshots_total",
    "Flight-recorder snapshots written to the data dir on incident "
    "(SLO burn threshold crossed, component flipped unhealthy), by "
    "incident reason", ("reason",))

# -- kernelscope: device-time truth (runtime/kernelscope.py) ------------------

dispatch_device_seconds = registry.histogram(
    "weaviate_tpu_dispatch_device_seconds",
    "Attributed device residency per coalesced dispatch, by compiled "
    "variant (index kind, padded batch bucket, k bucket) and attribution "
    "source: 'drain' = drain-thread stamps minus the sampled memcpy "
    "EWMA (zero-sync), 'wall' = dispatch wall window (sync engines and "
    "null-device bench stubs)",
    ("kind", "b", "k", "source"),
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5))
device_seconds_total = registry.counter(
    "weaviate_tpu_device_seconds_total",
    "Cumulative attributed device seconds apportioned per tenant "
    "(dispatch residency split across the requests it coalesced, "
    "weighted by rows scanned) — the interference signal for per-tenant "
    "QoS; sums to within the metering tolerance of total pipeline "
    "device residency",
    ("collection", "tenant"))

# -- perf gate (runtime/perfgate.py republishes these from the last
#    persisted benchkeeper verdict; see tools/benchkeeper) --------------------

bench_gate_ok = registry.gauge(
    "weaviate_tpu_bench_gate_ok",
    "1 when the last benchkeeper perf-gate verdict passed, 0 when it "
    "failed (regression, stale baseline, or missing metric)")
bench_gate_regressions = registry.gauge(
    "weaviate_tpu_bench_gate_regressions",
    "Out-of-band regressions in the last benchkeeper verdict")
bench_gate_stale = registry.gauge(
    "weaviate_tpu_bench_gate_stale_entries",
    "Baseline entries flagged stale (unexplained improvement beyond "
    "band) in the last benchkeeper verdict")
bench_metric_value = registry.gauge(
    "weaviate_tpu_bench_metric_value",
    "Last benchkeeper-checked value per baseline entry; the unit label "
    "carries the entry's unit (ms for device-attributed timings, qps, "
    "...)", ("entry", "unit"))
bench_delta_frac = registry.gauge(
    "weaviate_tpu_bench_delta_frac",
    "Fractional delta vs the baseline reference per entry, normalized "
    "so positive = regressing direction (slower scan / lower qps)",
    ("entry",))

# -- driftwatch (runtime/driftwatch.py: online recall/perf drift plane) -------

drift_gate_ok = registry.gauge(
    "weaviate_tpu_drift_gate_ok",
    "1 when no open driftwatch finding flips health (canary recall "
    "holds, live telemetry inside its benchkeeper bands), 0 during a "
    "drift incident")
drift_findings_total = registry.counter(
    "weaviate_tpu_drift_findings_total",
    "Driftwatch findings opened, by leg (canary = serving-path probe "
    "set, live = telemetry vs benchkeeper bands) and kind (recall, "
    "residency, regression, stale, refused)", ("leg", "kind"))
canary_recall = registry.gauge(
    "weaviate_tpu_canary_recall",
    "Worst canary recall@10 across a shard's vector spaces in the last "
    "driftwatch cycle, measured through the real query batcher against "
    "host-exact ground truth", ("collection", "shard"))

# -- jit compilation (runtime/compile_cache.py installs the listeners) --------

compile_cache_events = registry.counter(
    "weaviate_tpu_compile_cache_events_total",
    "Persistent compilation-cache lookups by outcome", ("event",))
jit_compile_duration = registry.histogram(
    "weaviate_tpu_jit_compile_seconds",
    "Backend compile time per jit signature (jax monitoring event key)",
    ("signature",),
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
             60.0, 120.0))


TEXT_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


def scrape(openmetrics: bool = False) -> tuple[bytes, str]:
    """One metrics scrape, shared by the REST /v1/metrics route and the
    monitoring port: run the read-point refreshes (benchkeeper verdict
    pickup, per-host HBM attribution, tailboard fold + SLO burn
    gauges), then render the negotiated exposition. Returns
    ``(body, content_type)``; every refresh is best-effort — a broken
    helper must never fail a scrape."""
    try:
        from weaviate_tpu.runtime import perfgate

        perfgate.refresh()
    except Exception:
        pass
    try:
        from weaviate_tpu.runtime.hbm_ledger import ledger

        ledger.refresh_host_gauge()
    except Exception:
        pass
    try:
        from weaviate_tpu.runtime import tailboard

        tailboard.scrape_refresh()
    except Exception:
        pass
    try:
        from weaviate_tpu.runtime import driftwatch

        driftwatch.scrape_refresh()
    except Exception:
        pass
    body = registry.expose(openmetrics=openmetrics).encode()
    return body, (OPENMETRICS_CONTENT_TYPE if openmetrics
                  else TEXT_CONTENT_TYPE)


def serve_metrics(host: str = "127.0.0.1", port: int = 2112):
    """Start the Prometheus /metrics listener (reference: a dedicated
    monitoring port, configure_api.go:148-153). Returns the HTTP server;
    .shutdown() stops it."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    import threading as _threading

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            accept = self.headers.get("Accept", "")
            body, ctype = scrape(
                openmetrics="application/openmetrics-text" in accept)
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True
    t = _threading.Thread(target=httpd.serve_forever, daemon=True,
                          name="metrics")
    t.start()
    return httpd
