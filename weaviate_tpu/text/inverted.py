"""Per-shard inverted index: searchable postings, filterable values, BM25F.

Reference: adapters/repos/db/inverted/ — the analyzer feeds three LSM bucket
families (mapcollection postings with term frequencies for BM25,
roaringset bitmaps for filterable props, prop-length tracker for BM25
normalization). Here the same three structures are host-RAM resident and
rebuilt from the objects bucket at startup (the shard replays objects the
same way it replays vectors into HBM); scoring is vectorized numpy — the
sparse-gather half of the hybrid pipeline whose dense half runs on TPU.

Scoring is **whole-posting vectorized** rather than WAND-pruned
(bm25_searcher.go:100 `wand`): gather the union of candidate doc ids with
np.unique, accumulate per-property weighted term frequencies with
np.add.at, and evaluate the closed-form BM25F score over the whole
candidate array at once. Pruning saves CPUs from scoring docs; a vector
unit prefers scoring everything in one pass.
"""

from __future__ import annotations

import math
import threading
from collections import defaultdict
from datetime import datetime, timezone

import numpy as np

from weaviate_tpu.schema.config import CollectionConfig, DataType, Property
from weaviate_tpu.text.stopwords import StopwordDetector
from weaviate_tpu.text.tokenizer import tokenize


def parse_date(value) -> float:
    """ISO-8601 (or epoch number) → epoch seconds."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value)
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    dt = datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


class _Postings:
    """Postings list for one (property, term): doc_id -> tf, with a cached
    numpy view for scoring (invalidated on mutation)."""

    __slots__ = ("tf", "_ids", "_tfs")

    def __init__(self):
        self.tf: dict[int, int] = {}
        self._ids = None
        self._tfs = None

    def add(self, doc_id: int, count: int):
        self.tf[doc_id] = self.tf.get(doc_id, 0) + count
        self._ids = None

    def remove(self, doc_id: int):
        if self.tf.pop(doc_id, None) is not None:
            self._ids = None

    def arrays(self):
        if self._ids is None:
            self._ids = np.fromiter(self.tf.keys(), dtype=np.int64,
                                    count=len(self.tf))
            self._tfs = np.fromiter(self.tf.values(), dtype=np.float32,
                                    count=len(self.tf))
        return self._ids, self._tfs

    def __len__(self):
        return len(self.tf)


def _infer_type(value) -> str | None:
    """Auto-schema-lite: map a raw property value to a DataType (reference:
    usecases/objects/auto_schema.go infers types for unknown props)."""
    if isinstance(value, bool):
        return DataType.BOOL
    if isinstance(value, int):
        return DataType.INT
    if isinstance(value, float):
        return DataType.NUMBER
    if isinstance(value, str):
        return DataType.TEXT
    if isinstance(value, dict) and {"latitude", "longitude"} <= set(value):
        return DataType.GEO
    if isinstance(value, (list, tuple)) and value:
        inner = _infer_type(value[0])
        return f"{inner}[]" if inner in (DataType.TEXT, DataType.INT,
                                         DataType.NUMBER, DataType.BOOL) else None
    return None


_NUMERIC_TYPES = {DataType.INT, DataType.NUMBER, DataType.DATE,
                  DataType.INT_ARRAY, DataType.NUMBER_ARRAY, DataType.DATE_ARRAY}


class InvertedIndex:
    """All three index families for one shard. Thread-safety: guarded by a
    single RLock (mutations come in under the shard lock anyway; queries
    take it only to snapshot postings references)."""

    def __init__(self, config: CollectionConfig):
        self.config = config
        inv = config.inverted
        self.stopwords = StopwordDetector(inv.stopwords_preset,
                                          inv.stopwords_additions,
                                          inv.stopwords_removals)
        self.k1 = inv.bm25_k1
        self.b = inv.bm25_b
        self._lock = threading.RLock()
        # searchable text postings: prop -> term -> _Postings
        self.searchable: dict[str, dict[str, _Postings]] = defaultdict(dict)
        # per-prop token counts for BM25 length normalization
        # (reference: new_prop_length_tracker.go JsonShardMetaData)
        self.doc_len: dict[str, dict[int, int]] = defaultdict(dict)
        self.total_len: dict[str, int] = defaultdict(int)
        # filterable exact-value sets: prop -> value_key -> set(doc_id)
        # (reference: roaringset strategy buckets)
        self.filterable: dict[str, dict[object, set[int]]] = defaultdict(
            lambda: defaultdict(set))
        # numeric/date values for range filters: prop -> doc_id -> float
        self.numeric: dict[str, dict[int, float]] = defaultdict(dict)
        # numeric/date ARRAY props: range filters need the per-value keys
        # for any-element semantics; scalar props are fully covered by
        # the numeric map
        self.array_props: set[str] = set()
        # geo coordinates: prop -> doc_id -> (lat, lon)
        self.geo: dict[str, dict[int, tuple[float, float]]] = defaultdict(dict)
        # null tracking (reference: IndexNullState)
        self.nulls: dict[str, set[int]] = defaultdict(set)
        self.doc_count = 0
        self._docs: set[int] = set()

    # -- schema helpers -------------------------------------------------------

    def _prop_schema(self, name: str, value) -> Property | None:
        p = self.config.property(name)
        if p is not None:
            return p
        dt = _infer_type(value)
        if dt is None:
            return None
        return Property(name=name, data_type=dt)

    # -- mutation -------------------------------------------------------------

    def index_object(self, obj) -> None:
        with self._lock:
            if obj.doc_id in self._docs:
                return
            self._docs.add(obj.doc_id)
            self.doc_count += 1
            for name, value in obj.properties.items():
                self._index_prop(obj.doc_id, name, value)
            if self.config.inverted.index_timestamps:
                self.numeric["_creationTimeUnix"][obj.doc_id] = obj.creation_time_ms
                self.numeric["_lastUpdateTimeUnix"][obj.doc_id] = obj.last_update_time_ms

    def unindex_object(self, obj) -> None:
        with self._lock:
            if obj.doc_id not in self._docs:
                return
            self._docs.discard(obj.doc_id)
            self.doc_count -= 1
            doc = obj.doc_id
            for name, value in obj.properties.items():
                prop = self._prop_schema(name, value)
                if prop is None:
                    continue
                if prop.index_searchable and prop.data_type in (
                        DataType.TEXT, DataType.TEXT_ARRAY):
                    terms = self.searchable.get(name, {})
                    for term in set(tokenize(value, prop.tokenization)):
                        p = terms.get(term)
                        if p is not None:
                            p.remove(doc)
                            if not p.tf:
                                del terms[term]
                    ln = self.doc_len[name].pop(doc, 0)
                    self.total_len[name] -= ln
                for vk in self._filter_keys(prop, value):
                    s = self.filterable[name].get(vk)
                    if s is not None:
                        s.discard(doc)
                        if not s:
                            del self.filterable[name][vk]
                self.numeric[name].pop(doc, None)
                self.geo[name].pop(doc, None)
            for s in self.nulls.values():
                s.discard(doc)
            if self.config.inverted.index_timestamps:
                self.numeric["_creationTimeUnix"].pop(doc, None)
                self.numeric["_lastUpdateTimeUnix"].pop(doc, None)

    def _index_prop(self, doc: int, name: str, value) -> None:
        prop = self._prop_schema(name, value)
        if prop is None:
            return
        if value is None:
            if self.config.inverted.index_null_state:
                self.nulls[name].add(doc)
            return
        if prop.index_searchable and prop.data_type in (
                DataType.TEXT, DataType.TEXT_ARRAY):
            tokens = tokenize(value, prop.tokenization)
            terms = self.searchable[name]
            counts: dict[str, int] = {}
            for t in tokens:
                counts[t] = counts.get(t, 0) + 1
            for t, c in counts.items():
                terms.setdefault(t, _Postings()).add(doc, c)
            self.doc_len[name][doc] = len(tokens)
            self.total_len[name] += len(tokens)
        if not prop.index_filterable:
            return
        for vk in self._filter_keys(prop, value):
            self.filterable[name][vk].add(doc)
        dt = prop.data_type
        if dt in (DataType.INT, DataType.NUMBER):
            self.numeric[name][doc] = float(value)
        elif dt == DataType.DATE:
            self.numeric[name][doc] = parse_date(value)
        elif dt in (DataType.INT_ARRAY, DataType.NUMBER_ARRAY):
            self.array_props.add(name)
            if value:
                # scalar index keeps min (for sorting); range filters use the
                # per-value filterable keys for any-element semantics
                self.numeric[name][doc] = float(min(value))
        elif dt == DataType.DATE_ARRAY:
            self.array_props.add(name)
            if value:
                self.numeric[name][doc] = min(parse_date(v) for v in value)
        elif dt == DataType.GEO:
            self.geo[name][doc] = (float(value["latitude"]),
                                   float(value["longitude"]))

    def _filter_keys(self, prop: Property, value) -> list:
        """Exact-match keys under which a value is filterable (text values
        are tokenized: reference Equal-on-text matches per-term)."""
        if value is None:
            return []
        dt = prop.data_type
        if dt in (DataType.TEXT, DataType.TEXT_ARRAY):
            return list(set(tokenize(value, prop.tokenization)))
        if dt in (DataType.BOOL, DataType.UUID):
            return [value]
        if dt in (DataType.BOOL_ARRAY, DataType.UUID_ARRAY):
            return list(set(value))
        if dt in (DataType.INT, DataType.NUMBER):
            return [float(value)]
        if dt == DataType.DATE:
            return [parse_date(value)]
        if dt in (DataType.INT_ARRAY, DataType.NUMBER_ARRAY):
            return [float(v) for v in set(value)]
        if dt == DataType.DATE_ARRAY:
            return [parse_date(v) for v in value]
        return []

    # -- BM25F scoring --------------------------------------------------------

    def searchable_props(self) -> list[str]:
        return [p.name for p in self.config.properties
                if p.index_searchable and p.data_type in (
                    DataType.TEXT, DataType.TEXT_ARRAY)] or \
               list(self.searchable.keys())

    def bm25_search(self, query: str, k: int = 10,
                    properties: list[str] | None = None,
                    allow_mask: np.ndarray | None = None):
        """BM25F over ``properties`` (``name^boost`` syntax supported).

        Returns (doc_ids [<=k] int64, scores [<=k] f32) descending.
        Reference: inverted/bm25_searcher.go:73 (BM25F), boosts parsed the
        same way (bm25_searcher.go propertyBoosts).
        """
        with self._lock:
            props: list[tuple[str, float]] = []
            for spec in (properties or self.searchable_props()):
                name, _, boost = spec.partition("^")
                props.append((name, float(boost) if boost else 1.0))
            n = max(self.doc_count, 1)

            # per-prop average length for the normalization term
            avg_len = {
                name: (self.total_len[name] / max(len(self.doc_len[name]), 1))
                or 1.0
                for name, _ in props
            }

            # the query analyzes per-property with THAT property's
            # tokenization (reference: bm25_searcher analyzes per field);
            # a term's df = docs containing it in ANY searched property
            # (BM25F treats props as fields of one doc)
            term_fields: dict[str, list] = {}
            for name, boost in props:
                sch = self.config.property(name)
                tok = sch.tokenization if sch is not None else "word"
                for term in self.stopwords.filter(
                        sorted(set(tokenize(query, tok)))):
                    term_fields.setdefault(term, []).append((name, boost))
            if not term_fields:
                return np.empty(0, np.int64), np.empty(0, np.float32)

            term_rows = []  # (idf, [(ids, tfs, boost, prop_name)])
            for term, tf_props in sorted(term_fields.items()):
                fields = []
                df_docs: set[int] = set()
                for name, boost in tf_props:
                    p = self.searchable.get(name, {}).get(term)
                    if p is None or not len(p):
                        continue
                    ids, tfs = p.arrays()
                    fields.append((ids, tfs, boost, name))
                    df_docs.update(p.tf.keys())
                if not fields:
                    continue
                df = len(df_docs)
                idf = math.log(1.0 + (n - df + 0.5) / (df + 0.5))
                term_rows.append((idf, fields))
            if not term_rows:
                return np.empty(0, np.int64), np.empty(0, np.float32)

            # candidate universe = union of all postings
            all_ids = np.unique(np.concatenate(
                [ids for _, fields in term_rows for ids, *_ in fields]))
            if allow_mask is not None:
                keep = all_ids[(all_ids < len(allow_mask))]
                keep = keep[allow_mask[keep]]
                all_ids = keep
            if len(all_ids) == 0:
                return np.empty(0, np.int64), np.empty(0, np.float32)

            scores = np.zeros(len(all_ids), dtype=np.float32)
            k1, b = self.k1, self.b
            for idf, fields in term_rows:
                # BM25F: per-field length-normalized tf, weighted-summed
                # across fields, then saturated once
                tf_acc = np.zeros(len(all_ids), dtype=np.float32)
                for ids, tfs, boost, name in fields:
                    pos = np.searchsorted(all_ids, ids)
                    inb = (pos < len(all_ids))
                    pos_c = np.clip(pos, 0, len(all_ids) - 1)
                    hit = inb & (all_ids[pos_c] == ids)
                    if not hit.any():
                        continue
                    dl = self.doc_len[name]
                    lens = np.fromiter(
                        (dl.get(int(d), 0) for d in ids[hit]),
                        dtype=np.float32, count=int(hit.sum()))
                    norm = 1.0 - b + b * lens / avg_len[name]
                    np.add.at(tf_acc, pos_c[hit],
                              boost * tfs[hit] / np.maximum(norm, 1e-9))
                scores += idf * tf_acc / (k1 + tf_acc)

            k_eff = min(k, len(all_ids))
            top = np.argpartition(-scores, k_eff - 1)[:k_eff]
            order = top[np.argsort(-scores[top], kind="stable")]
            return all_ids[order], scores[order]
