"""Per-shard inverted index: searchable postings, filterable values, BM25F.

Reference: adapters/repos/db/inverted/ — the analyzer feeds three LSM bucket
families (mapcollection postings with term frequencies for BM25,
roaringset bitmaps for filterable props, prop-length tracker for BM25
normalization). This implementation writes through the same three bucket
shapes at put time (reference: updateInvertedIndexLSM,
shard_write_put.go:454):

- ``inv_search``  (map)        key = prop\\x00term -> {doc: [tf, prop_len]}
                               (reference MapPair packs tf + propLength the
                               same way for BM25, inverted/bm25_searcher.go)
- ``inv_filter``  (roaringset) key = prop\\x00 + typed value key
- ``inv_numeric`` (roaringset) key = prop\\x00 + order-preserving f64 —
                               range filters are LSM range scans
- ``inv_geo``     (replace)    key = prop\\x00 + be64 doc -> (lat, lon)
- ``inv_null``    (roaringset) key = prop (reference IndexNullState)
- ``inv_meta``    (replace)    per-prop length aggregates + doc count

Opening a shard therefore does NOT replay objects into RAM: postings are
read (and LRU-cached) on demand at query time, merged across segments by
the LSM read path — reopen cost is O(segments), not O(objects).

Scoring is **MaxScore-pruned vectorized BM25F** (the vectorized analog of
the reference's WAND pivot pruning, bm25_searcher.go:100, block-max at
:551): terms sort by a cached per-posting score upper bound, the candidate
universe is the union of only the highest-impact ("essential") postings,
and the loop stops as soon as the summed upper bounds of the remaining
terms fall below the running k-th best score — provably identical top-k to
exhaustive scoring. High-df stop-like terms never expand the candidate
set; they are probed at candidate positions by binary search. Within the
candidate set, scoring stays whole-array vectorized (np.add.at
accumulation, closed-form BM25F) — pruning picks which docs to score, the
vector unit scores them in one pass.
"""

from __future__ import annotations

import math
import struct
import threading
from collections import OrderedDict
from datetime import datetime, timezone

import numpy as np

from weaviate_tpu.schema.config import CollectionConfig, DataType, Property
from weaviate_tpu.text.stopwords import StopwordDetector
from weaviate_tpu.text.tokenizer import tokenize

B_SEARCH = "inv_search"
B_FILTER = "inv_filter"
B_NUMERIC = "inv_numeric"
B_GEO = "inv_geo"
B_NULL = "inv_null"
B_META = "inv_meta"

_ALL_DOCS = b"\x00__all__"
_SEP = b"\x00"


def parse_date(value) -> float:
    """ISO-8601 (or epoch number) → epoch seconds."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value)
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    dt = datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


def _enc_f64(x: float) -> bytes:
    """Order-preserving float64 encoding: byte order == numeric order."""
    x = float(x)
    if x == 0.0:
        x = 0.0  # -0.0 and +0.0 must share a key (dict semantics: -0.0 == 0.0)
    (u,) = struct.unpack(">Q", struct.pack(">d", x))
    if u & 0x8000000000000000:
        u = ~u & 0xFFFFFFFFFFFFFFFF
    else:
        u |= 0x8000000000000000
    return struct.pack(">Q", u)


def _dec_f64(b: bytes) -> float:
    (u,) = struct.unpack(">Q", b)
    if u & 0x8000000000000000:
        u &= 0x7FFFFFFFFFFFFFFF
    else:
        u = ~u & 0xFFFFFFFFFFFFFFFF
    return struct.unpack(">d", struct.pack(">Q", u))[0]


def _value_key(value) -> bytes | None:
    """Typed exact-match key for one filterable value (text tokens keyed
    as 't'+utf8 so LIKE can range-scan the text vocabulary)."""
    if isinstance(value, bool):
        return b"b\x01" if value else b"b\x00"
    if isinstance(value, (int, float)):
        return b"f" + _enc_f64(float(value))
    if isinstance(value, str):
        return b"t" + value.encode()
    return None


def _infer_type(value) -> str | None:
    """Auto-schema-lite: map a raw property value to a DataType (reference:
    usecases/objects/auto_schema.go infers types for unknown props)."""
    if isinstance(value, bool):
        return DataType.BOOL
    if isinstance(value, int):
        return DataType.INT
    if isinstance(value, float):
        return DataType.NUMBER
    if isinstance(value, str):
        return DataType.TEXT
    if isinstance(value, dict) and {"latitude", "longitude"} <= set(value):
        return DataType.GEO
    if isinstance(value, (list, tuple)) and value:
        inner = _infer_type(value[0])
        return f"{inner}[]" if inner in (DataType.TEXT, DataType.INT,
                                         DataType.NUMBER, DataType.BOOL) else None
    return None


_NUMERIC_TYPES = {DataType.INT, DataType.NUMBER, DataType.DATE,
                  DataType.INT_ARRAY, DataType.NUMBER_ARRAY, DataType.DATE_ARRAY}


class GeoGrid:
    """1-degree grid buckets over (lat, lon) rows, cell-sorted for
    range lookups by ``np.searchsorted``.

    Cells are keyed ``lat_cell * 360 + lon_cell``; the rows of one lat
    band are contiguous in the sorted arrays, so a query circle resolves
    to at most two searchsorted intervals per intersected lat band
    (longitude wrap splits one). Candidate rows then get the exact
    vectorized haversine — sublinear in the corpus for any selective
    radius, degrading gracefully to the full scan for planet-sized ones.
    """

    CELL_DEG = 1.0
    _LON_CELLS = 360

    def __init__(self, ids: np.ndarray, lats: np.ndarray, lons: np.ndarray):
        lat_c = np.clip(np.floor(lats + 90.0).astype(np.int64), 0, 179)
        lon_c = np.clip(np.floor(lons + 180.0).astype(np.int64), 0, 359)
        key = lat_c * self._LON_CELLS + lon_c
        order = np.argsort(key, kind="stable")
        self.ids = ids[order]
        self.lats = lats[order]
        self.lons = lons[order]
        self._keys = key[order]

    def __len__(self):
        return len(self.ids)

    def candidate_positions(self, lat: float, lon: float,
                            max_m: float) -> np.ndarray:
        """Positional indices (into the grid-sorted arrays) of every row
        whose cell intersects the query circle."""
        if not len(self.ids):
            return np.empty(0, np.int64)
        r_earth = 6_371_000.0
        ang = min(max_m / r_earth, math.pi)  # query radius, radians
        lat_span = math.degrees(ang)
        lat_lo = max(lat - lat_span, -90.0)
        lat_hi = min(lat + lat_span, 90.0)
        row_lo = int(np.clip(np.floor(lat_lo + 90.0), 0, 179))
        row_hi = int(np.clip(np.floor(lat_hi + 90.0), 0, 179))
        clat_r = math.radians(lat)
        cos_ang = math.cos(ang)

        def half_span_deg(phi_deg: float) -> float:
            """Longitude half-span of the circle at latitude phi (exact
            spherical law of cosines, solved for delta-lon)."""
            phi = math.radians(phi_deg)
            den = math.cos(clat_r) * math.cos(phi)
            num = cos_ang - math.sin(clat_r) * math.sin(phi)
            if den <= 1e-12:
                return 180.0 if num <= 0 else 0.0
            c = num / den
            if c <= -1.0:
                return 180.0
            if c >= 1.0:
                return 0.0
            return math.degrees(math.acos(c))

        # latitude maximizing the span (tangent point of the circle)
        sin_t = math.sin(clat_r) / max(cos_ang, 1e-12) if cos_ang > 0 else 2.0
        phi_star = math.degrees(math.asin(sin_t)) if abs(sin_t) <= 1 else None
        out = []
        for row in range(row_lo, row_hi + 1):
            lo_deg, hi_deg = row - 90.0, row - 89.0
            samples = [lo_deg, hi_deg]
            if phi_star is not None and lo_deg <= phi_star <= hi_deg:
                samples.append(phi_star)
            if lo_deg <= lat <= hi_deg:
                samples.append(lat)
            lon_span = max(half_span_deg(p) for p in samples)
            # cell granularity: pad by one cell to cover partial overlap
            lon_span = min(lon_span + self.CELL_DEG, 180.0)
            if lon_span >= 180.0 or row == 0 or row == 179:
                intervals = [(0, self._LON_CELLS - 1)]
            else:
                c_lo = math.floor(lon - lon_span + 180.0)
                c_hi = math.floor(lon + lon_span + 180.0)
                if c_lo < 0:
                    intervals = [(0, min(c_hi, 359)),
                                 (c_lo % 360, 359)]
                elif c_hi > 359:
                    intervals = [(c_lo, 359), (0, c_hi % 360)]
                else:
                    intervals = [(c_lo, c_hi)]
            base = row * self._LON_CELLS
            for a, b in intervals:
                lo = np.searchsorted(self._keys, base + a, side="left")
                hi = np.searchsorted(self._keys, base + b, side="right")
                if hi > lo:
                    out.append(np.arange(lo, hi, dtype=np.int64))
        if not out:
            return np.empty(0, np.int64)
        return np.concatenate(out)


class _LRU:
    """Tiny LRU for decoded posting/bitmap arrays (hot query terms)."""

    def __init__(self, cap: int = 65536):
        self.cap = cap
        self.d: OrderedDict = OrderedDict()

    def get(self, key):
        v = self.d.get(key)
        if v is not None:
            self.d.move_to_end(key)
        return v

    def put(self, key, value):
        self.d[key] = value
        self.d.move_to_end(key)
        if len(self.d) > self.cap:
            self.d.popitem(last=False)

    def pop(self, key):
        self.d.pop(key, None)

    def clear(self):
        self.d.clear()


class InvertedIndex:
    """All six bucket families for one shard, with RAM LRU caches in front.

    Thread-safety: a single RLock guards cache + meta mutations (writes
    come in under the shard lock anyway; queries take it to snapshot).
    """

    def __init__(self, config: CollectionConfig, store=None):
        self.config = config
        inv = config.inverted
        self.stopwords = StopwordDetector(inv.stopwords_preset,
                                          inv.stopwords_additions,
                                          inv.stopwords_removals)
        self.k1 = inv.bm25_k1
        self.b = inv.bm25_b
        self._lock = threading.RLock()
        if store is None:
            # tests construct an index without a shard store: back it with
            # an in-RAM KVStore in a temp dir? No — a throwaway tmpdir.
            import tempfile

            from weaviate_tpu.storage.kv import KVStore

            self._own_dir = tempfile.TemporaryDirectory(prefix="inv-")
            store = KVStore(self._own_dir.name)
        self._store = store
        # postings_schema: the searchable map values are strictly
        # doc -> (tf, len), unlocking the native C++ memtable (kv.py)
        self.searchable_bucket = store.bucket(B_SEARCH, "map",
                                              postings_schema=True)
        self.filter_bucket = store.bucket(B_FILTER, "roaringset")
        self.numeric_bucket = store.bucket(B_NUMERIC, "roaringset")
        self.geo_bucket = store.bucket(B_GEO, "replace")
        self.null_bucket = store.bucket(B_NULL, "roaringset")
        self.meta_bucket = store.bucket(B_META, "replace")
        self._post_cache = _LRU()
        self._bitmap_cache = _LRU()
        self._geo_cache: dict[str, tuple] = {}
        # bumped under _lock on every mutation; readers capture it before
        # the (unlocked) bucket read and only cache if unchanged — a
        # concurrent write's invalidation can never be overwritten by a
        # stale fill
        self._version = 0
        self._meta = self.meta_bucket.get(b"__aggregates__") or {
            "doc_count": 0, "props": {}}
        # props that hold numeric/date ARRAYS: range semantics are
        # any-element, answered by the per-element numeric keys
        self.array_props: set[str] = set(self._meta.get("arrays", []))

    # -- schema helpers -------------------------------------------------------

    def _prop_schema(self, name: str, value) -> Property | None:
        p = self.config.property(name)
        if p is not None:
            return p
        dt = _infer_type(value)
        if dt is None:
            return None
        return Property(name=name, data_type=dt)

    @property
    def doc_count(self) -> int:
        return int(self._meta.get("doc_count", 0))

    def _save_meta(self):
        self._meta["arrays"] = sorted(self.array_props)
        self.meta_bucket.put(b"__aggregates__", self._meta)

    def reconcile_doc_count(self, actual: int) -> None:
        """Re-anchor doc_count to the objects bucket at shard open: a crash
        between index_objects and the objects-bucket commit leaves ghost doc
        ids counted here forever (they're never unindexed), drifting BM25
        idf/avg-length. Reconciling at open bounds the drift to one crash
        window."""
        with self._lock:
            if self.doc_count != actual:
                self._meta["doc_count"] = int(actual)
                self._save_meta()
                self._version += 1

    # -- mutation -------------------------------------------------------------

    def index_object(self, obj) -> None:
        self.index_objects([obj])

    def index_objects(self, objs) -> None:
        """Batch insert: one WAL frame per bucket family per batch
        (reference: updateInvertedIndexLSM per put, shard_write_put.go:454)."""
        search_upd: dict[bytes, dict] = {}
        # analyzer-output concat jobs: (prefix, keys, entry_offs, cols...)
        search_jobs: list[tuple] = []
        filter_jobs: list[tuple] = []
        filter_add: dict[bytes, set] = {}
        numeric_add: dict[bytes, set] = {}
        null_add: dict[bytes, set] = {}
        geo_puts: list[tuple[bytes, object]] = []
        all_docs: set[int] = set()
        prop_len_delta: dict[str, list] = {}  # prop -> [total_delta, count_delta]

        # native batch analyzer: one FFI call per (text prop, batch) for
        # ASCII values (csrc wn_analyze_batch — the import hot loop,
        # reference inverted/analyzer.go per put). Non-ASCII values and
        # odd shapes keep the unicode-aware Python path; ASCII-ness is a
        # property of the value, so index/unindex key derivation stays
        # consistent either way.
        text_handled = self._index_text_batch(
            objs, search_jobs, filter_jobs, prop_len_delta)

        for obj in objs:
            doc = obj.doc_id
            all_docs.add(doc)
            for name, value in obj.properties.items():
                if (name, doc) in text_handled:
                    continue  # batch analyzer wrote postings + filter keys
                self._collect_index_prop(
                    doc, name, value, search_upd, filter_add, numeric_add,
                    null_add, geo_puts, prop_len_delta)
            if self.config.inverted.index_timestamps:
                for tname, tval in (
                        ("_creationTimeUnix", obj.creation_time_ms),
                        ("_lastUpdateTimeUnix", obj.last_update_time_ms)):
                    nk = tname.encode() + _SEP + _enc_f64(float(tval))
                    numeric_add.setdefault(nk, set()).add(doc)

        with self._lock:
            if search_upd:
                self.searchable_bucket.map_set_many(search_upd.items())
            for pfx, keys, eoffs, docs_c, tfs_c, lens_c in search_jobs:
                self.searchable_bucket.map_set_columns_concat(
                    keys, eoffs, docs_c, tfs_c, lens_c, prefix=pfx)
            for pfx, keys, eoffs, docs_c in filter_jobs:
                self.filter_bucket.bitmap_add_concat(
                    keys, eoffs, docs_c.astype(np.uint64), prefix=pfx)
            filter_add.setdefault(_ALL_DOCS, set()).update(all_docs)
            self.filter_bucket.bitmap_add_many(filter_add.items())
            if numeric_add:
                self.numeric_bucket.bitmap_add_many(numeric_add.items())
            if null_add:
                self.null_bucket.bitmap_add_many(null_add.items())
            if geo_puts:
                self.geo_bucket.put_many(geo_puts)
            self._meta["doc_count"] = self.doc_count + len(objs)
            props_meta = self._meta.setdefault("props", {})
            for prop, (dl, dc) in prop_len_delta.items():
                pm = props_meta.setdefault(prop, {"total_len": 0, "len_count": 0})
                pm["total_len"] += dl
                pm["len_count"] += dc
            self._save_meta()
            self._version += 1
            # cache invalidation for every touched key; when a batch
            # touches more keys than the cache could plausibly hold hot,
            # one clear beats tens of thousands of per-key pops (the pops
            # were 5% of the whole import profile)
            for k in search_upd:
                self._post_cache.pop(k)
            n_touched = sum(len(j[1]) for j in search_jobs)
            if n_touched > 2048 or n_touched > len(self._post_cache.d):
                self._post_cache.clear()
            else:
                for pfx, keys, _e, *_cols in search_jobs:
                    for k in keys:
                        self._post_cache.pop(pfx + k)
            n_touched = sum(len(j[1]) for j in filter_jobs)
            if n_touched > 2048 or n_touched > len(self._bitmap_cache.d):
                self._bitmap_cache.clear()
            else:
                for pfx, keys, _e, _d in filter_jobs:
                    for k in keys:
                        self._bitmap_cache.pop((B_FILTER, pfx + k))
            for k in filter_add:
                self._bitmap_cache.pop((B_FILTER, k))
            for k in numeric_add:
                self._bitmap_cache.pop((B_NUMERIC, k))
            for k in null_add:
                self._bitmap_cache.pop((B_NULL, k))
            for k, _ in geo_puts:
                self._geo_cache.pop(k.split(_SEP, 1)[0].decode(), None)

    _JOIN_BY_TOKENIZATION = {"word": "\x01", "lowercase": " ",
                             "whitespace": " "}

    def _index_text_batch(self, objs, search_jobs, filter_jobs,
                          prop_len_delta) -> set:
        """Batch-analyze ASCII text properties through the native analyzer
        (one FFI call per prop per batch). Returns the (prop, doc) pairs
        fully handled — postings, text filter keys, and prop-length
        aggregates — identically to the per-value Python path. Output
        lands in ``search_jobs``/``filter_jobs`` as whole-prop concat
        columns for the storage layer's one-call native writes."""
        from weaviate_tpu import native

        if not native.available():
            return set()
        handled: set = set()
        jobs: dict[str, tuple[list[int], list[str]]] = {}
        props: dict[str, Property] = {}
        for obj in objs:
            for name, value in obj.properties.items():
                prop = props.get(name)
                if prop is None:
                    prop = self._prop_schema(name, value)
                    if prop is None or prop.data_type not in (
                            DataType.TEXT, DataType.TEXT_ARRAY):
                        continue
                    props[name] = prop
                if prop.data_type not in (DataType.TEXT,
                                          DataType.TEXT_ARRAY):
                    continue
                if not (prop.index_searchable or prop.index_filterable):
                    continue
                if isinstance(value, str):
                    if not value.isascii():
                        continue
                elif isinstance(value, (list, tuple)):
                    join = self._JOIN_BY_TOKENIZATION.get(prop.tokenization)
                    if join is None or not all(
                            isinstance(v, str) and v.isascii()
                            for v in value):
                        continue  # field-mode arrays keep the Python path
                    value = join.join(value)
                else:
                    continue
                docs, vals = jobs.setdefault(name, ([], []))
                docs.append(obj.doc_id)
                vals.append(value)
                handled.add((name, obj.doc_id))
        for name, (docs, vals) in jobs.items():
            prop = props[name]
            res = native.analyze_batch(vals, prop.tokenization)
            if res is None:  # lib vanished mid-flight: Python path
                for d in docs:
                    handled.discard((name, d))
                continue
            terms, eoffs, rows, tfs, row_tokens = res
            pfx = name.encode() + _SEP
            docs_arr = np.asarray(docs, dtype=np.int64)
            # whole-prop CONCAT columns: the per-term entry layout is the
            # analyzer's own (entry_offs into docs/tfs/lens); the storage
            # layer applies + WAL-frames them in one native call per prop
            # (kv.py map_set_columns_concat / bitmap_add_concat)
            keys = terms  # analyzer emits bytes keys directly
            docs_col = docs_arr[rows]
            if prop.index_searchable:
                search_jobs.append((pfx, keys, eoffs, docs_col, tfs,
                                    row_tokens[rows]))
                d = prop_len_delta.setdefault(name, [0, 0])
                d[0] += int(row_tokens.sum())
                d[1] += len(docs)
            if prop.index_filterable:
                filter_jobs.append((pfx + b"t", keys, eoffs, docs_col))
        return handled

    def _collect_index_prop(self, doc, name, value, search_upd, filter_add,
                            numeric_add, null_add, geo_puts, prop_len_delta):
        prop = self._prop_schema(name, value)
        if prop is None:
            return
        pfx = name.encode() + _SEP
        if value is None:
            if self.config.inverted.index_null_state:
                null_add.setdefault(name.encode(), set()).add(doc)
            return
        if prop.index_searchable and prop.data_type in (
                DataType.TEXT, DataType.TEXT_ARRAY):
            tokens = tokenize(value, prop.tokenization)
            counts: dict[str, int] = {}
            for t in tokens:
                counts[t] = counts.get(t, 0) + 1
            n_tok = len(tokens)
            for t, c in counts.items():
                search_upd.setdefault(pfx + t.encode(), {})[doc] = [c, n_tok]
            d = prop_len_delta.setdefault(name, [0, 0])
            d[0] += n_tok
            d[1] += 1
        if not prop.index_filterable:
            return
        for vk in self._filter_keys(prop, value):
            bk = _value_key(vk)
            if bk is not None:
                cur = filter_add.get(pfx + bk)
                if cur is None:
                    filter_add[pfx + bk] = {doc}
                elif isinstance(cur, set):
                    cur.add(doc)
                else:
                    # the batch analyzer stored an ndarray for this key
                    # (ASCII docs) — widen to a set to absorb this doc
                    s = set(cur.tolist())
                    s.add(doc)
                    filter_add[pfx + bk] = s
        dt = prop.data_type
        if dt in (DataType.INT, DataType.NUMBER):
            numeric_add.setdefault(pfx + _enc_f64(float(value)), set()).add(doc)
        elif dt == DataType.DATE:
            numeric_add.setdefault(pfx + _enc_f64(parse_date(value)),
                                   set()).add(doc)
        elif dt in (DataType.INT_ARRAY, DataType.NUMBER_ARRAY):
            self.array_props.add(name)
            for v in set(value):
                numeric_add.setdefault(pfx + _enc_f64(float(v)), set()).add(doc)
        elif dt == DataType.DATE_ARRAY:
            self.array_props.add(name)
            for v in set(value):
                numeric_add.setdefault(pfx + _enc_f64(parse_date(v)),
                                       set()).add(doc)
        elif dt == DataType.GEO:
            geo_puts.append((pfx + struct.pack(">Q", doc),
                             [float(value["latitude"]),
                              float(value["longitude"])]))

    def unindex_object(self, obj) -> None:
        self.unindex_objects([obj])

    def unindex_objects(self, objs) -> None:
        """Remove docs' postings by re-deriving their keys from the CURRENT
        schema — batched: one apply pass per bucket family for the whole
        batch (the per-object form cost ~390 µs/update through repeated
        bitmap passes). Consequently changing a property's tokenization,
        data type, or the stopword config after objects are indexed leaves
        stale postings for already-indexed docs on later delete/update
        (the keys recomputed under the new config differ from those
        written). The reference forbids mutating tokenization in place for
        the same reason; stopword-config updates remain allowed for parity
        with the reference's mutable invertedIndexConfig, at the
        documented cost that existing docs need a reindex to pick the
        change up cleanly."""
        if not objs:
            return
        search_del: dict[bytes, set] = {}
        filter_del: dict[bytes, set] = {}
        numeric_del: dict[bytes, set] = {}
        null_del: dict[bytes, set] = {}
        geo_del: list[bytes] = []
        prop_len_delta: dict[str, list] = {}

        for obj in objs:
            self._collect_unindex(obj, search_del, filter_del, numeric_del,
                                  null_del, geo_del, prop_len_delta)

        with self._lock:
            if search_del:
                self.searchable_bucket.map_delete_many(search_del.items())
            all_docs = filter_del.setdefault(_ALL_DOCS, set())
            all_docs.update(o.doc_id for o in objs)
            self.filter_bucket.bitmap_remove_many(filter_del.items())
            if numeric_del:
                self.numeric_bucket.bitmap_remove_many(numeric_del.items())
            if null_del:
                self.null_bucket.bitmap_remove_many(null_del.items())
            for k in geo_del:
                self.geo_bucket.delete(k)
            self._meta["doc_count"] = max(self.doc_count - len(objs), 0)
            props_meta = self._meta.setdefault("props", {})
            for prop, (dl, dc) in prop_len_delta.items():
                pm = props_meta.setdefault(prop,
                                           {"total_len": 0, "len_count": 0})
                pm["total_len"] += dl
                pm["len_count"] += dc
            self._save_meta()
            self._version += 1
            for k in search_del:
                self._post_cache.pop(k)
            for k in filter_del:
                self._bitmap_cache.pop((B_FILTER, k))
            for k in numeric_del:
                self._bitmap_cache.pop((B_NUMERIC, k))
            for k in null_del:
                self._bitmap_cache.pop((B_NULL, k))
            for k in geo_del:
                self._geo_cache.pop(k.split(_SEP, 1)[0].decode(), None)

    def _collect_unindex(self, obj, search_del, filter_del, numeric_del,
                         null_del, geo_del, prop_len_delta) -> None:
        doc = obj.doc_id
        for name, value in obj.properties.items():
            prop = self._prop_schema(name, value)
            if prop is None:
                continue
            pfx = name.encode() + _SEP
            if value is None:
                null_del.setdefault(name.encode(), set()).add(doc)
                continue
            if prop.index_searchable and prop.data_type in (
                    DataType.TEXT, DataType.TEXT_ARRAY):
                tokens = tokenize(value, prop.tokenization)
                for term in set(tokens):
                    search_del.setdefault(pfx + term.encode(), set()).add(doc)
                d = prop_len_delta.setdefault(name, [0, 0])
                d[0] -= len(tokens)
                d[1] -= 1
            for vk in self._filter_keys(prop, value):
                bk = _value_key(vk)
                if bk is not None:
                    filter_del.setdefault(pfx + bk, set()).add(doc)
            dt = prop.data_type
            if dt in (DataType.INT, DataType.NUMBER):
                numeric_del.setdefault(pfx + _enc_f64(float(value)),
                                       set()).add(doc)
            elif dt == DataType.DATE:
                numeric_del.setdefault(pfx + _enc_f64(parse_date(value)),
                                       set()).add(doc)
            elif dt in (DataType.INT_ARRAY, DataType.NUMBER_ARRAY):
                for v in set(value):
                    numeric_del.setdefault(pfx + _enc_f64(float(v)),
                                           set()).add(doc)
            elif dt == DataType.DATE_ARRAY:
                for v in set(value):
                    numeric_del.setdefault(pfx + _enc_f64(parse_date(v)),
                                           set()).add(doc)
            elif dt == DataType.GEO:
                geo_del.append(pfx + struct.pack(">Q", doc))

        if self.config.inverted.index_timestamps:
            for tname, tval in (("_creationTimeUnix", obj.creation_time_ms),
                                ("_lastUpdateTimeUnix", obj.last_update_time_ms)):
                nk = tname.encode() + _SEP + _enc_f64(float(tval))
                numeric_del.setdefault(nk, set()).add(doc)

    def _filter_keys(self, prop: Property, value) -> list:
        """Exact-match keys under which a value is filterable (text values
        are tokenized: reference Equal-on-text matches per-term)."""
        if value is None:
            return []
        dt = prop.data_type
        if dt in (DataType.TEXT, DataType.TEXT_ARRAY):
            return list(set(tokenize(value, prop.tokenization)))
        if dt in (DataType.BOOL, DataType.UUID):
            return [value]
        if dt in (DataType.BOOL_ARRAY, DataType.UUID_ARRAY):
            return list(set(value))
        if dt in (DataType.INT, DataType.NUMBER):
            return [float(value)]
        if dt == DataType.DATE:
            return [parse_date(value)]
        if dt in (DataType.INT_ARRAY, DataType.NUMBER_ARRAY):
            return [float(v) for v in set(value)]
        if dt == DataType.DATE_ARRAY:
            return [parse_date(v) for v in value]
        return []

    # -- read accessors (filters.py + BM25 consume these) ---------------------

    def postings(self, prop: str, term: str):
        """(ids int64 sorted, tfs f32, lens f32) for one (prop, term)."""
        return self.postings_with_bounds(prop, term)[:3]

    def postings_with_bounds(self, prop: str, term: str):
        """(ids, tfs, lens, max_tf, min_len) — the bounds are computed once
        at posting load and cached; they feed the MaxScore per-term score
        upper bound (the analog of the reference's WAND block-max impacts,
        bm25_searcher.go:551) at O(1) per query."""
        from weaviate_tpu.runtime.metrics import (postings_cache_hits,
                                                  postings_cache_misses)

        key = prop.encode() + _SEP + term.encode()
        with self._lock:
            hit = self._post_cache.get(key)
            if hit is not None:
                postings_cache_hits.inc()
                return hit
            version = self._version
        postings_cache_misses.inc()
        m = self.searchable_bucket.get_map(key)
        if not m:
            out = (np.empty(0, np.int64), np.empty(0, np.float32),
                   np.empty(0, np.float32), 0.0, 1.0)
        else:
            ids = np.fromiter(m.keys(), dtype=np.int64, count=len(m))
            order = np.argsort(ids)
            ids = ids[order]
            tfs = np.fromiter((v[0] for v in m.values()), dtype=np.float32,
                              count=len(m))[order]
            lens = np.fromiter((v[1] for v in m.values()), dtype=np.float32,
                               count=len(m))[order]
            out = (ids, tfs, lens, float(tfs.max()), float(lens.min()))
        with self._lock:
            if self._version == version:
                self._post_cache.put(key, out)
        return out

    def _bitmap(self, bucket_name: str, bucket, key: bytes) -> np.ndarray:
        ck = (bucket_name, key)
        with self._lock:
            hit = self._bitmap_cache.get(ck)
            if hit is not None:
                return hit
            version = self._version
        arr = bucket.get_bitmap(key)
        with self._lock:
            if self._version == version:
                self._bitmap_cache.put(ck, arr)
        return arr

    def all_docs(self) -> np.ndarray:
        """Sorted uint64 ids of live docs."""
        return self._bitmap(B_FILTER, self.filter_bucket, _ALL_DOCS)

    def filterable_ids(self, prop: str, value) -> np.ndarray:
        bk = _value_key(value)
        if bk is None:
            return np.empty(0, np.uint64)
        return self._bitmap(B_FILTER, self.filter_bucket,
                            prop.encode() + _SEP + bk)

    def null_ids(self, prop: str) -> np.ndarray:
        return self._bitmap(B_NULL, self.null_bucket, prop.encode())

    def text_vocab(self, prop: str):
        """Iterate (token, ids) over the text vocabulary of a prop (LIKE)."""
        pfx = prop.encode() + _SEP + b"t"
        for k, v in self.filter_bucket.iter_range(pfx, pfx + b"\xff" * 4):
            from weaviate_tpu import native

            ids = native.difference_sorted(v["add"], v["del"])
            if len(ids):
                yield k[len(pfx):].decode(), ids

    def numeric_range_ids(self, prop: str, lo: float | None, hi: float | None,
                          lo_incl: bool = True, hi_incl: bool = False):
        """Union of doc bitmaps for values in the given range — an LSM
        range scan over order-preserving keys (reference: searcher.go
        range row readers over roaringset)."""
        from weaviate_tpu import native

        pfx = prop.encode() + _SEP
        if lo is None:
            start = pfx
        else:
            start = pfx + _enc_f64(lo)
            if not lo_incl:
                start += b"\x00"
        if hi is None:
            stop = pfx + b"\xff" * 9
        else:
            stop = pfx + _enc_f64(hi)
            if hi_incl:
                stop += b"\x00"
        parts = []
        for _k, v in self.numeric_bucket.iter_range(start, stop):
            ids = native.difference_sorted(v["add"], v["del"])
            if len(ids):
                parts.append(ids)
        if not parts:
            return np.empty(0, np.uint64)
        # one concatenate+unique instead of repeated pairwise unions —
        # a wide range over mostly-unique values would otherwise go
        # quadratic in the number of distinct keys
        return np.unique(np.concatenate(parts))

    def geo_arrays(self, prop: str):
        """(ids int64, lats f64, lons f64) for every doc with a geo value
        on ``prop`` (grid-sorted order)."""
        g = self.geo_grid(prop)
        return g.ids, g.lats, g.lons

    def geo_grid(self, prop: str) -> "GeoGrid":
        """Grid-bucketed geo index for ``prop`` — materialized from the
        geo bucket once and cached; WITHIN_GEO_RANGE touches only the
        cells intersecting the query circle instead of every geo row
        (the reference keeps a per-property geo vector index,
        adapters/repos/db/vector/geo/geo.go:35 — on TPU a host grid +
        vectorized haversine over the candidate cells is both simpler
        and sublinear)."""
        with self._lock:
            hit = self._geo_cache.get(prop)
            if hit is not None:
                return hit
            version = self._version
        pfx = prop.encode() + _SEP
        ids, lats, lons = [], [], []
        for k, v in self.geo_bucket.iter_range(pfx, pfx + b"\xff" * 9):
            (doc,) = struct.unpack(">Q", k[len(pfx):])
            ids.append(doc)
            lats.append(v[0])
            lons.append(v[1])
        grid = GeoGrid(np.asarray(ids, np.int64),
                       np.asarray(lats, np.float64),
                       np.asarray(lons, np.float64))
        with self._lock:
            if self._version == version:
                self._geo_cache[prop] = grid
        return grid

    def avg_len(self, prop: str) -> float:
        pm = self._meta.get("props", {}).get(prop)
        if not pm or not pm.get("len_count"):
            return 1.0
        return max(pm["total_len"] / pm["len_count"], 1e-9)

    # -- BM25F scoring --------------------------------------------------------

    def searchable_props(self) -> list[str]:
        props = [p.name for p in self.config.properties
                 if p.index_searchable and p.data_type in (
                     DataType.TEXT, DataType.TEXT_ARRAY)]
        if props:
            return props
        # fall back to every prop with length aggregates (auto-schema'd)
        return sorted(self._meta.get("props", {}).keys())

    def _bm25_plan(self, query: str,
                   properties: list[str] | None = None):
        """Shared BM25F planning prologue (host scorer AND the
        hybridplane's posting pack): parse ``name^boost`` specs, analyze
        the query per property, load postings, and compute per-term
        idf / MaxScore upper bounds. Returns ``(term_rows, avg_len)``
        with ``term_rows`` a list of ``(idf, ub, fields)`` in sorted-term
        order (fields = ``(ids, tfs, lens, boost, prop_name)``), or None
        when no term has a live posting."""
        props: list[tuple[str, float]] = []
        for spec in (properties or self.searchable_props()):
            name, _, boost = spec.partition("^")
            props.append((name, float(boost) if boost else 1.0))
        n = max(self.doc_count, 1)
        avg_len = {name: self.avg_len(name) for name, _ in props}

        # the query analyzes per-property with THAT property's
        # tokenization (reference: bm25_searcher analyzes per field);
        # a term's df = docs containing it in ANY searched property
        # (BM25F treats props as fields of one doc)
        term_fields: dict[str, list] = {}
        for name, boost in props:
            sch = self.config.property(name)
            tok = sch.tokenization if sch is not None else "word"
            for term in self.stopwords.filter(
                    sorted(set(tokenize(query, tok)))):
                term_fields.setdefault(term, []).append((name, boost))
        if not term_fields:
            return None

        k1, b = self.k1, self.b
        term_rows = []  # (idf, ub, [(ids, tfs, lens, boost, prop_name)])
        for term, tf_props in sorted(term_fields.items()):
            fields = []
            df_union = None
            s_max = 0.0  # upper bound on the field-summed normalized tf
            for name, boost in tf_props:
                ids, tfs, lens, max_tf, min_len = \
                    self.postings_with_bounds(name, term)
                if not len(ids):
                    continue
                fields.append((ids, tfs, lens, boost, name))
                norm_lo = max(1.0 - b + b * min_len / avg_len[name], 1e-9)
                s_max += boost * max_tf / norm_lo
                df_union = ids if df_union is None else \
                    np.union1d(df_union, ids)
            if not fields:
                continue
            df = len(df_union)
            idf = math.log(1.0 + (n - df + 0.5) / (df + 0.5))
            # tf saturation is monotone: score_t(doc) <= idf * s/(k1+s)
            ub = idf * s_max / (k1 + s_max)
            term_rows.append((idf, ub, fields))
        if not term_rows:
            return None
        return term_rows, avg_len

    def bm25_search(self, query: str, k: int = 10,
                    properties: list[str] | None = None,
                    allow_mask: np.ndarray | None = None):
        """BM25F over ``properties`` (``name^boost`` syntax supported).

        Returns (doc_ids [<=k] int64, scores [<=k] f32) descending.
        Reference: inverted/bm25_searcher.go:73 (BM25F), boosts parsed the
        same way (bm25_searcher.go propertyBoosts).
        """
        plan = self._bm25_plan(query, properties)
        if plan is None:
            return np.empty(0, np.int64), np.empty(0, np.float32)
        term_rows, avg_len = plan
        k1, b = self.k1, self.b

        def score_candidates(cand: np.ndarray) -> np.ndarray:
            """Exact BM25F over ``cand`` (sorted) across ALL query terms —
            non-candidate postings are probed by binary search, never
            expanded."""
            scores = np.zeros(len(cand), dtype=np.float32)
            for idf, _ub, fields in term_rows:
                # BM25F: per-field length-normalized tf, weighted-summed
                # across fields, then saturated once
                tf_acc = np.zeros(len(cand), dtype=np.float32)
                for ids, tfs, lens, boost, name in fields:
                    # probe DIRECTION matters: search the candidates into
                    # the posting — O(|cand| log |posting|) — so a 1M-id
                    # stop-term posting costs log-time per candidate, not a
                    # full pass (the WAND property)
                    pos = np.searchsorted(ids, cand)
                    inb = (pos < len(ids))
                    pos_c = np.clip(pos, 0, len(ids) - 1)
                    hit = inb & (ids[pos_c] == cand)
                    if not hit.any():
                        continue
                    src = pos_c[hit]
                    norm = 1.0 - b + b * lens[src] / avg_len[name]
                    tf_acc[hit] += boost * tfs[src] / np.maximum(norm, 1e-9)
                scores += idf * tf_acc / (k1 + tf_acc)
            return scores

        # --- MaxScore pruning (reference: WAND pivot, bm25_searcher.go:100,
        # :551). Terms sort by score upper bound; the candidate universe is
        # the union of the first j ("essential") postings only. Any doc
        # outside it scores <= sum of the remaining UBs, so once that tail
        # is below the running k-th best score the top-k is provably
        # identical to exhaustive scoring — high-df stop-like terms never
        # expand the universe, they are only probed at candidate positions.
        term_rows.sort(key=lambda t: -t[1])
        ubs = np.asarray([t[1] for t in term_rows], dtype=np.float64)
        tail_ub = np.concatenate([np.cumsum(ubs[::-1])[::-1], [0.0]])

        def allowed(ids: np.ndarray) -> np.ndarray:
            if allow_mask is None:
                return ids
            keep = ids[ids < len(allow_mask)]
            return keep[allow_mask[keep]]

        cand = np.empty(0, np.int64)
        scores = np.empty(0, np.float32)
        n_terms = len(term_rows)
        for j in range(1, n_terms + 1):
            new_ids = allowed(np.unique(np.concatenate(
                [ids for ids, *_ in term_rows[j - 1][2]])))
            # incremental: docs already scored carry their (exact, all-term)
            # scores over — only genuinely new candidates get a scoring pass,
            # so every doc is scored exactly once across all iterations
            fresh = new_ids
            if len(cand):
                pos = np.searchsorted(cand, new_ids)
                pos_c = np.clip(pos, 0, len(cand) - 1)
                fresh = new_ids[(pos >= len(cand)) | (cand[pos_c] != new_ids)]
            if len(fresh):
                fresh_scores = score_candidates(fresh)
                merged = np.concatenate([cand, fresh])
                order = np.argsort(merged, kind="stable")
                cand = merged[order]
                scores = np.concatenate([scores, fresh_scores])[order]
            if len(cand) == 0:
                continue
            if len(cand) >= k:
                kth = float(np.partition(scores, len(scores) - k)[len(scores) - k])
                if tail_ub[j] < kth:
                    break
        self.last_bm25_stats = {
            "terms": n_terms,
            "essential_terms": j if term_rows else 0,
            "candidates": int(len(cand)),
            "postings_total": int(sum(
                len(ids) for _, _, fields in term_rows
                for ids, *_ in fields)),
        }
        if len(cand) == 0:
            return np.empty(0, np.int64), np.empty(0, np.float32)

        k_eff = min(k, len(cand))
        top = np.argpartition(-scores, k_eff - 1)[:k_eff]
        order = top[np.argsort(-scores[top], kind="stable")]
        return cand[order], scores[order]

    def bm25_pack(self, query: str,
                  properties: list[str] | None = None,
                  allow_mask: np.ndarray | None = None, *,
                  max_candidates: int = 4096):
        """Plan one query for DEVICE scoring (the hybridplane pack).

        Same prologue as ``bm25_search`` (analysis, postings, idf, ub
        ordering) but instead of scoring, the ALLOWED UNION of every
        term's postings ships as the candidate universe — a superset of
        the MaxScore essential union, so the device top-k is provably
        the exhaustive top-k — as dense per-(term, prop) segment planes
        over the candidate axis (ops/bm25.py layout). Segments pack in
        ub-DESCENDING term order with fields in query order, mirroring
        the host scorer's accumulation order for f32 parity. Returns a
        dict of host arrays + scalars (the shard layer adds store slots
        and fusion params to make a ``SparseOperand``), or None when the
        device path should not take the query (no live terms, empty
        allowed union, or a candidate universe past ``max_candidates``
        — the planner's budget gate; callers fall back to the host
        scorer)."""
        plan = self._bm25_plan(query, properties)
        if plan is None:
            return None
        term_rows, avg_len = plan
        term_rows = sorted(term_rows, key=lambda t: -t[1])
        all_ids = np.unique(np.concatenate(
            [ids for _idf, _ub, fields in term_rows
             for ids, *_ in fields]))
        if allow_mask is not None:
            keep = all_ids[all_ids < len(allow_mask)]
            cand = keep[allow_mask[keep]]
        else:
            cand = all_ids
        postings_total = int(sum(
            len(ids) for _idf, _ub, fields in term_rows
            for ids, *_ in fields))
        if len(cand) == 0 or len(cand) > max_candidates:
            return None
        c = len(cand)
        seg_tf, seg_len, seg_term, seg_boost, seg_avg = [], [], [], [], []
        idf_arr = np.zeros(len(term_rows), np.float32)
        for t_idx, (idf, _ub, fields) in enumerate(term_rows):
            idf_arr[t_idx] = idf
            for ids, tfs, lens, boost, name in fields:
                pos = np.searchsorted(ids, cand)
                inb = pos < len(ids)
                pos_c = np.clip(pos, 0, len(ids) - 1)
                hit = inb & (ids[pos_c] == cand)
                row_tf = np.zeros(c, np.float32)
                row_len = np.zeros(c, np.float32)
                src = pos_c[hit]
                row_tf[hit] = tfs[src]
                row_len[hit] = lens[src]
                seg_tf.append(row_tf)
                seg_len.append(row_len)
                seg_term.append(t_idx)
                seg_boost.append(boost)
                seg_avg.append(avg_len[name])
        stats = {
            "terms": len(term_rows),
            "candidates": c,
            "postings_total": postings_total,
            # posting entries the planner did NOT materialize as
            # candidate columns (multi-term/multi-prop overlap + allow
            # filtering) — the explain plane's "pruned frac"
            "pruned_frac": round(1.0 - c / max(postings_total, 1), 6),
        }
        return {
            "doc_ids": cand.astype(np.int64),
            "seg_tf": np.stack(seg_tf),
            "seg_len": np.stack(seg_len),
            "seg_term": np.asarray(seg_term, np.int32),
            "seg_boost": np.asarray(seg_boost, np.float32),
            "seg_avg": np.asarray(seg_avg, np.float32),
            "idf": idf_arr,
            "k1": float(self.k1),
            "b": float(self.b),
            # host-rounded f32(1 - b): numpy's weak scalar cast makes
            # the host's ``1.0 - b + <f32>`` effectively f32((1-b)) + x;
            # shipping the pre-rounded value keeps device parity exact
            "one_minus_b": float(np.float32(1.0 - self.b)),
            "stats": stats,
        }
