"""Stopword presets.

Reference: adapters/repos/db/inverted/stopwords/ (preset "en" ≈ Lucene's
english list; configurable additions/removals per class,
entities/models/StopwordConfig).
"""

from __future__ import annotations

_EN = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split()
)

_PRESETS = {"en": _EN, "none": frozenset()}


class StopwordDetector:
    def __init__(self, preset: str = "en", additions=(), removals=()):
        base = _PRESETS.get(preset)
        if base is None:
            raise ValueError(f"unknown stopword preset {preset!r}")
        self._words = (set(base) | {w.lower() for w in additions}) - {
            w.lower() for w in removals
        }

    def is_stopword(self, token: str) -> bool:
        return token.lower() in self._words

    def filter(self, tokens: list[str]) -> list[str]:
        return [t for t in tokens if t.lower() not in self._words]
