"""Hybrid search fusion: merge sparse (BM25) and dense (vector) rankings.

Reference: usecases/traverser/hybrid/hybrid_fusion.go —
``FusionRanked`` (:22, reciprocal-rank fusion with alpha weights) and
``FusionRelativeScore`` (:87, min-max normalized score blending); the
orchestration (parallel sparse+dense searches) mirrors hybrid/searcher.go:74.

These are also the hybridplane's PARITY ORACLE: the device fusion merge
(ops/bm25.py::fuse_topk) must rank identically to these functions,
including the dict-insertion-order tie-break (sparse leg first, then
unseen dense entries). Both fusions return ``(score, result)`` pairs —
the input result objects are NEVER mutated, because they may be shared
across concurrent hybrid queries (two overlapping fusions writing
``res.score`` in place used to clobber each other's rankings).
"""

from __future__ import annotations


def fusion_ranked(result_sets: list[list], weights: list[float],
                  k: int = 10) -> list[tuple[float, object]]:
    """Reciprocal-rank fusion. Each result keeps its best contribution:
    score_i = sum over sets of weight / (60 + rank). Reference:
    hybrid_fusion.go:22 (the constant 60 is the reference's, :36).
    Returns ``(fused_score, result)`` pairs, best first; the result
    objects pass through untouched."""
    fused: dict[str, tuple[float, object]] = {}
    for results, weight in zip(result_sets, weights):
        for rank, res in enumerate(results):
            add = weight / (60.0 + rank)
            prev = fused.get(res.uuid)
            fused[res.uuid] = (add + (prev[0] if prev else 0.0),
                              prev[1] if prev else res)
    return sorted(fused.values(), key=lambda t: -t[0])[:k]


def fusion_relative_score(result_sets: list[list], weights: list[float],
                          k: int = 10) -> list[tuple[float, object]]:
    """Min-max normalize each set's scores to [0,1], blend by weight.
    Reference: hybrid_fusion.go:87 (FusionRelativeScore). Distances from
    the dense set must already be converted to similarity scores
    (higher = better) by the caller. Returns ``(fused_score, result)``
    pairs, best first; the result objects pass through untouched."""
    fused: dict[str, tuple[float, object]] = {}
    for results, weight in zip(result_sets, weights):
        if not results:
            continue
        scores = [r.score for r in results]
        lo, hi = min(scores), max(scores)
        span = (hi - lo) or 1.0
        for res in results:
            norm = (res.score - lo) / span if hi > lo else 1.0
            add = weight * norm
            prev = fused.get(res.uuid)
            fused[res.uuid] = (add + (prev[0] if prev else 0.0),
                              prev[1] if prev else res)
    return sorted(fused.values(), key=lambda t: -t[0])[:k]
