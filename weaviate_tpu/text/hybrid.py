"""Hybrid search fusion: merge sparse (BM25) and dense (vector) rankings.

Reference: usecases/traverser/hybrid/hybrid_fusion.go —
``FusionRanked`` (:22, reciprocal-rank fusion with alpha weights) and
``FusionRelativeScore`` (:87, min-max normalized score blending); the
orchestration (parallel sparse+dense searches) mirrors hybrid/searcher.go:74.
"""

from __future__ import annotations


def fusion_ranked(result_sets: list[list], weights: list[float],
                  k: int = 10) -> list:
    """Reciprocal-rank fusion. Each result keeps its best contribution:
    score_i = sum over sets of weight / (60 + rank). Reference:
    hybrid_fusion.go:22 (the constant 60 is the reference's, :36)."""
    fused: dict[str, tuple[float, object]] = {}
    for results, weight in zip(result_sets, weights):
        for rank, res in enumerate(results):
            add = weight / (60.0 + rank)
            prev = fused.get(res.uuid)
            fused[res.uuid] = (add + (prev[0] if prev else 0.0),
                              prev[1] if prev else res)
    out = sorted(fused.values(), key=lambda t: -t[0])[:k]
    results = []
    for score, res in out:
        res.score = score
        results.append(res)
    return results


def fusion_relative_score(result_sets: list[list], weights: list[float],
                          k: int = 10) -> list:
    """Min-max normalize each set's scores to [0,1], blend by weight.
    Reference: hybrid_fusion.go:87 (FusionRelativeScore). Distances from
    the dense set must already be converted to similarity scores
    (higher = better) by the caller."""
    fused: dict[str, tuple[float, object]] = {}
    for results, weight in zip(result_sets, weights):
        if not results:
            continue
        scores = [r.score for r in results]
        lo, hi = min(scores), max(scores)
        span = (hi - lo) or 1.0
        for res in results:
            norm = (res.score - lo) / span if hi > lo else 1.0
            add = weight * norm
            prev = fused.get(res.uuid)
            fused[res.uuid] = (add + (prev[0] if prev else 0.0),
                              prev[1] if prev else res)
    out = sorted(fused.values(), key=lambda t: -t[0])[:k]
    results = []
    for score, res in out:
        res.score = score
        results.append(res)
    return results
