"""Text search: tokenization, inverted index, BM25F, hybrid fusion.

Reference: adapters/repos/db/inverted/ (analyzer, BM25 searcher, filter
searcher) + usecases/traverser/hybrid/ (fusion).
"""

from weaviate_tpu.text.tokenizer import tokenize
from weaviate_tpu.text.inverted import InvertedIndex

__all__ = ["tokenize", "InvertedIndex"]
