"""Tokenizers.

Reference: entities/models tokenization enum + adapters/repos/db/inverted/
analyzer.go and helpers/tokenizer.go. Four modes with reference semantics:

- ``word``:       lowercase, split on any non-alphanumeric rune
- ``lowercase``:  lowercase, split on whitespace
- ``whitespace``: split on whitespace, case preserved
- ``field``:      trim whitespace, the whole value is one token
"""

from __future__ import annotations

import re

_NON_ALNUM = re.compile(r"[^0-9A-Za-zÀ-ɏЀ-ӿ一-鿿]+")

TOKENIZATIONS = ("word", "lowercase", "whitespace", "field")


def tokenize(text, tokenization: str = "word") -> list[str]:
    """Tokenize a text value (str or list of str)."""
    if isinstance(text, (list, tuple)):
        out: list[str] = []
        for t in text:
            out.extend(tokenize(t, tokenization))
        return out
    if text is None:
        return []
    text = str(text)
    if tokenization == "word":
        return [t for t in _NON_ALNUM.split(text.lower()) if t]
    if tokenization == "lowercase":
        return text.lower().split()
    if tokenization == "whitespace":
        return text.split()
    if tokenization == "field":
        t = text.strip()
        return [t] if t else []
    raise ValueError(f"unknown tokenization {tokenization!r}")
