"""Query-feature layer: aggregations, sorting, autocut, cursor listing.

Reference: adapters/repos/db/aggregator/, adapters/repos/db/sorter/,
entities/autocut/.
"""

from weaviate_tpu.query.aggregator import PropertyAggregator, aggregate_objects, combine_partials
from weaviate_tpu.query.autocut import autocut
from weaviate_tpu.query.sorter import sort_objects

__all__ = [
    "PropertyAggregator",
    "aggregate_objects",
    "combine_partials",
    "autocut",
    "sort_objects",
]
