"""Object sorting by property paths / id / special keys.

Reference: adapters/repos/db/sorter/ — comparators for every property
data type with explicit null ordering (basic_comparators.go), applied to
result sets before pagination.
"""

from __future__ import annotations

from weaviate_tpu.query.aggregator import _parse_date


def _sort_key_value(obj, path: str):
    """Extract a comparable value; None sorts last regardless of order."""
    if path in ("_id", "id", "uuid"):
        return obj.uuid
    if path == "_creationTimeUnix":
        return getattr(obj, "creation_time_ms", 0)
    if path == "_lastUpdateTimeUnix":
        return getattr(obj, "last_update_time_ms", 0)
    v = obj.properties.get(path)
    if isinstance(v, str):
        try:
            return _parse_date(v)  # dates sort on the timeline
        except ValueError:
            return v
    if isinstance(v, list):
        return len(v)  # reference sorts arrays by length
    if isinstance(v, bool):
        return int(v)
    return v


def sort_objects(objects: list, sort_specs: list[dict]) -> list:
    """Stable multi-key sort. ``sort_specs``: [{"path": "name",
    "order": "asc"|"desc"}, ...] — applied right-to-left so the first
    spec dominates (reference: objects_sorter.go)."""
    out = list(objects)
    for spec in reversed(sort_specs):
        path = spec["path"] if isinstance(spec["path"], str) else spec["path"][0]
        desc = spec.get("order", "asc") == "desc"

        keyed = [(_sort_key_value(o, path), o) for o in out]
        nones = [o for kv, o in keyed if kv is None]
        present = [(kv, o) for kv, o in keyed if kv is not None]
        # mixed-type guard: compare within the dominant type, others go last
        try:
            present.sort(key=lambda t: t[0], reverse=desc)
        except TypeError:
            present.sort(key=lambda t: (str(type(t[0])), str(t[0])), reverse=desc)
        out = [o for _, o in present] + nones
    return out
