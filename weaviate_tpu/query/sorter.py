"""Object sorting by property paths / id / special keys.

Reference: adapters/repos/db/sorter/ — comparators for every property
data type with explicit null ordering (basic_comparators.go), applied to
result sets before pagination; search results carry their distances
through the sort (objects_sorter.go:21 Sort(objects, distances)).
"""

from __future__ import annotations

from weaviate_tpu.query.aggregator import _parse_date


def _sort_key_value(obj, path: str):
    """Extract a comparable value; None sorts last regardless of order."""
    if path in ("_id", "id", "uuid"):
        return obj.uuid
    if path == "_creationTimeUnix":
        return getattr(obj, "creation_time_ms", 0)
    if path == "_lastUpdateTimeUnix":
        return getattr(obj, "last_update_time_ms", 0)
    v = obj.properties.get(path)
    if isinstance(v, str):
        try:
            return _parse_date(v)  # dates sort on the timeline
        except ValueError:
            return v
    if isinstance(v, list):
        return len(v)  # reference sorts arrays by length
    if isinstance(v, bool):
        return int(v)
    return v


def _multikey_sort(items: list, sort_specs: list[dict], key_of) -> list:
    """Stable multi-key sort applied right-to-left so the first spec
    dominates (reference: objects_sorter.go). ``key_of(item, path)``
    extracts the comparable; None sorts last regardless of order, and
    mixed-type keys compare within the dominant type (others go last)."""
    out = list(items)
    for spec in reversed(sort_specs):
        path = spec["path"] if isinstance(spec["path"], str) else spec["path"][0]
        desc = spec.get("order", "asc") == "desc"
        keyed = [(key_of(it, path), it) for it in out]
        nones = [it for kv, it in keyed if kv is None]
        present = [(kv, it) for kv, it in keyed if kv is not None]
        try:
            present.sort(key=lambda t: t[0], reverse=desc)
        except TypeError:
            present.sort(key=lambda t: (str(type(t[0])), str(t[0])),
                         reverse=desc)
        out = [it for _, it in present] + nones
    return out


def sort_objects(objects: list, sort_specs: list[dict]) -> list:
    """Stable multi-key sort of StorageObjects. ``sort_specs``:
    [{"path": "name", "order": "asc"|"desc"}, ...]."""
    return _multikey_sort(objects, sort_specs, _sort_key_value)


def sort_search_results(results: list, sort_specs: list[dict]) -> list:
    """Sort SEARCH results (SearchResult: .object/.distance/.score) —
    the reference's objects_sorter.go:21 Sort(objects, distances) keeps
    the object<->distance pairing through the sort; the special paths
    ``_distance``/``distance`` and ``_score`` sort the search metric
    itself, composing with property keys in one stable multi-key sort."""

    def key_of(r, path: str):
        if path in ("_distance", "distance"):
            return r.distance
        if path in ("_score", "score"):
            return r.score
        if r.object is None:
            return r.uuid if path in ("_id", "id", "uuid") else None
        return _sort_key_value(r.object, path)

    return _multikey_sort(results, sort_specs, key_of)
