"""Autocut: truncate a ranked result list at natural score jumps.

Reference semantics (entities/autocut/autocut.go): normalize the score
curve to the unit square, subtract the diagonal, and cut at the index of
the ``cut_off``-th local maximum of the residual — i.e. the point just
before the curve's steepest drops. Works on distances (ascending) and on
scores mapped to ascending order alike.
"""

from __future__ import annotations

import numpy as np


def autocut(values, cut_off: int) -> int:
    """Return the cut index into ``values`` (ascending ranking metric).

    ``cut_off`` is the number of score "jumps" to keep; <=0 disables the
    cut (returns len(values)).
    """
    values = np.asarray(values, dtype=np.float32)
    n = len(values)
    if n <= 1 or cut_off <= 0:
        return n
    span = values[-1] - values[0]
    if span == 0.0:
        return n
    # residual of the normalized curve above the unit diagonal
    x = np.linspace(0.0, 1.0, n, dtype=np.float32)
    resid = (values - values[0]) / span - x

    extrema = 0
    for i in range(1, n):
        if i == n - 1:
            is_peak = n > 1 and resid[i] > resid[i - 1] and resid[i] > resid[i - 2]
        else:
            is_peak = resid[i] > resid[i - 1] and resid[i] > resid[i + 1]
        if is_peak:
            extrema += 1
            if extrema >= cut_off:
                return i
    return n


def autocut_results(results: list, cut_off: int, by: str = "distance") -> list:
    """Apply autocut to a list of SearchResults ranked by ``by``.

    ``by="distance"`` uses ascending distances; ``by="score"`` negates
    descending scores into an ascending curve first.
    """
    if cut_off <= 0 or len(results) <= 1:
        return results
    if by == "distance":
        vals = [r.distance for r in results]
    else:
        vals = [-(r.score or 0.0) for r in results]
    return results[: autocut(vals, cut_off)]
