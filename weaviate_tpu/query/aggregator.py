"""Property aggregations with shard-combinable partials.

Reference: adapters/repos/db/aggregator/ — numerical (count/min/max/mean/
median/mode/sum, numerical.go), text topOccurrences (text.go), boolean
totals+percentages (boolean.go), date min/max/median/mode (date.go);
cross-shard merge in shard_combiner.go.

Design: each shard folds its objects into a serializable *partial*
(counts + value counters, mirroring the reference's ``valueCounter``
maps), partials merge associatively across shards/nodes, and the final
numbers are computed once at the coordinator. Median and mode are exact
because the partial carries the full value histogram, not a sketch.
"""

from __future__ import annotations

from collections import Counter
from datetime import datetime, timezone

NUMERICAL_AGGS = ("count", "minimum", "maximum", "mean", "median", "mode", "sum")
TEXT_AGGS = ("count", "topOccurrences")
BOOLEAN_AGGS = ("count", "totalTrue", "totalFalse", "percentageTrue", "percentageFalse")
DATE_AGGS = ("count", "minimum", "maximum", "median", "mode")


def _parse_date(v: str) -> float:
    """ISO-8601 → epoch seconds (dates aggregate on their timeline order)."""
    s = v.replace("Z", "+00:00")
    dt = datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


class PropertyAggregator:
    """Accumulates one property's values; type inferred from data."""

    def __init__(self):
        self.kind: str | None = None  # numerical | text | boolean | date
        self.count = 0
        self.sum = 0.0
        self.values = Counter()  # histogram: value -> occurrences

    # -- fold ----------------------------------------------------------------

    def add(self, value) -> None:
        if value is None:
            return
        if isinstance(value, bool):
            kind = "boolean"
        elif isinstance(value, (int, float)):
            kind = "numerical"
        elif isinstance(value, str):
            kind = "text"
            try:
                _parse_date(value)
                kind = "date"
            except ValueError:
                pass
        elif isinstance(value, list):
            for v in value:
                self.add(v)
            return
        else:
            return
        if self.kind is None:
            self.kind = kind
        elif self.kind != kind:
            # mixed types: degrade to text, keep counting occurrences
            # (a date-looking string among text keeps the text kind)
            if {self.kind, kind} == {"text", "date"}:
                self.kind = "text"
            else:
                return
        self.count += 1
        if kind == "numerical":
            self.sum += float(value)
            self.values[float(value)] += 1
        else:
            self.values[value] += 1

    # -- partial protocol ------------------------------------------------------

    def to_partial(self) -> dict:
        return {
            "kind": self.kind,
            "count": self.count,
            "sum": self.sum,
            "values": [[k, c] for k, c in self.values.items()],
        }

    @classmethod
    def from_partial(cls, d: dict) -> "PropertyAggregator":
        agg = cls()
        agg.kind = d["kind"]
        agg.count = d["count"]
        agg.sum = d["sum"]
        agg.values = Counter({(tuple(k) if isinstance(k, list) else k): c
                              for k, c in d["values"]})
        return agg

    def merge(self, other: "PropertyAggregator") -> None:
        if other.kind is None:
            return
        if self.kind is None:
            self.kind = other.kind
        elif self.kind != other.kind:
            if {self.kind, other.kind} == {"text", "date"}:
                self.kind = "text"
            else:
                return
        self.count += other.count
        self.sum += other.sum
        self.values.update(other.values)

    # -- finalize ----------------------------------------------------------------

    def _sorted_numeric(self):
        if self.kind == "date":
            return sorted(self.values.items(), key=lambda kv: _parse_date(kv[0]))
        return sorted(self.values.items())

    def _median(self):
        """Exact median from the histogram (reference computes from
        valueCounter, numerical.go buildPairsFromCounts)."""
        target = self.count // 2
        seen = 0
        pairs = self._sorted_numeric()
        for i, (v, c) in enumerate(pairs):
            seen += c
            if seen > target:
                return v
            if seen == target and self.count % 2 == 0 and self.kind == "numerical":
                nxt = pairs[i + 1][0] if i + 1 < len(pairs) else v
                return (v + nxt) / 2.0
        return pairs[-1][0] if pairs else None

    def _mode(self):
        if not self.values:
            return None
        return max(self.values.items(), key=lambda kv: (kv[1],))[0]

    def finalize(self, requested: list[str] | None = None, top_occurrences_limit: int = 5) -> dict:
        if self.kind is None or self.count == 0:
            return {"count": 0}
        if self.kind == "numerical":
            out = {
                "count": self.count,
                "minimum": min(self.values),
                "maximum": max(self.values),
                "mean": self.sum / self.count,
                "median": self._median(),
                "mode": self._mode(),
                "sum": self.sum,
            }
        elif self.kind == "boolean":
            t = self.values.get(True, 0)
            f = self.values.get(False, 0)
            out = {
                "count": self.count,
                "totalTrue": t,
                "totalFalse": f,
                "percentageTrue": t / self.count,
                "percentageFalse": f / self.count,
            }
        elif self.kind == "date":
            pairs = self._sorted_numeric()
            out = {
                "count": self.count,
                "minimum": pairs[0][0],
                "maximum": pairs[-1][0],
                "median": self._median(),
                "mode": self._mode(),
            }
        else:  # text
            top = self.values.most_common(top_occurrences_limit)
            out = {
                "count": self.count,
                "type": "text",
                "topOccurrences": [{"value": v, "occurs": c} for v, c in top],
            }
        out["type"] = self.kind if self.kind != "text" else "text"
        if requested:
            keep = set(requested) | {"type"}
            out = {k: v for k, v in out.items() if k in keep}
        return out


# -- shard-level fold ----------------------------------------------------------


def aggregate_objects(objects, properties: list[str] | None = None,
                      group_by: str | None = None) -> dict:
    """Fold an iterable of StorageObjects into a partial aggregation dict.

    Returns {"count": N, "properties": {name: partial}, "groups": {value:
    {"count": n, "properties": ...}}} — everything JSON-serializable so it
    can cross node boundaries (reference: per-shard Aggregate then
    shard_combiner.go merge).
    """
    props = properties or []
    total = 0
    aggs = {p: PropertyAggregator() for p in props}
    groups: dict = {}
    for obj in objects:
        total += 1
        vals = obj.properties
        for p in props:
            aggs[p].add(vals.get(p))
        if group_by is not None:
            gv = vals.get(group_by)
            gvs = gv if isinstance(gv, list) else [gv]
            for g in gvs:
                if g is None:
                    continue
                grp = groups.setdefault(
                    g, {"count": 0, "properties": {p: PropertyAggregator() for p in props}})
                grp["count"] += 1
                for p in props:
                    grp["properties"][p].add(vals.get(p))
    return {
        "count": total,
        "properties": {p: a.to_partial() for p, a in aggs.items()},
        "groups": {
            _group_key(g): {
                "value": g,
                "count": grp["count"],
                "properties": {p: a.to_partial() for p, a in grp["properties"].items()},
            }
            for g, grp in groups.items()
        },
    }


def _group_key(v) -> str:
    # JSON object keys must be strings; keep the raw value in the payload
    return f"{type(v).__name__}:{v}"


def combine_partials(partials: list[dict]) -> dict:
    """Associative merge of shard partials (reference: shard_combiner.go)."""
    total = 0
    aggs: dict[str, PropertyAggregator] = {}
    groups: dict[str, dict] = {}
    for part in partials:
        total += part["count"]
        for p, d in part["properties"].items():
            a = PropertyAggregator.from_partial(d)
            if p in aggs:
                aggs[p].merge(a)
            else:
                aggs[p] = a
        for key, grp in part.get("groups", {}).items():
            dst = groups.get(key)
            if dst is None:
                groups[key] = {
                    "value": grp["value"],
                    "count": grp["count"],
                    "properties": {p: PropertyAggregator.from_partial(d)
                                   for p, d in grp["properties"].items()},
                }
            else:
                dst["count"] += grp["count"]
                for p, d in grp["properties"].items():
                    a = PropertyAggregator.from_partial(d)
                    if p in dst["properties"]:
                        dst["properties"][p].merge(a)
                    else:
                        dst["properties"][p] = a
    return {"count": total, "properties": aggs, "groups": groups}


def finalize_aggregation(combined: dict, requested: dict[str, list[str]] | None = None,
                         top_occurrences_limit: int = 5) -> dict:
    """Combined partial → API-shaped result (entities/aggregation/result.go)."""
    req = requested or {}
    out = {
        "meta": {"count": combined["count"]},
        "properties": {
            p: a.finalize(req.get(p), top_occurrences_limit)
            for p, a in combined["properties"].items()
        },
    }
    if combined["groups"]:
        grps = []
        for grp in combined["groups"].values():
            grps.append({
                "groupedBy": {"value": grp["value"]},
                "meta": {"count": grp["count"]},
                "properties": {
                    p: a.finalize(req.get(p), top_occurrences_limit)
                    for p, a in grp["properties"].items()
                },
            })
        grps.sort(key=lambda g: -g["meta"]["count"])
        out["groups"] = grps
    return out
