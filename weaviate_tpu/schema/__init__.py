"""Schema: collection configs, properties, vector index configs.

Maps the reference's entities/schema + entities/vectorindex config surface
and the usecases/schema handler validation (schema/handler.go:102).
"""

from weaviate_tpu.schema.config import (
    CollectionConfig,
    InvertedIndexConfig,
    Property,
    ShardingConfig,
    MultiTenancyConfig,
    ReplicationConfig,
    VectorConfig,
    VectorIndexConfig,
    DataType,
)

__all__ = [
    "CollectionConfig",
    "InvertedIndexConfig",
    "Property",
    "ShardingConfig",
    "MultiTenancyConfig",
    "ReplicationConfig",
    "VectorConfig",
    "VectorIndexConfig",
    "DataType",
]
