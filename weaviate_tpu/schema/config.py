"""Collection (class) configuration model.

Reference parity:
- class schema + properties: entities/models (Class, Property), validated in
  usecases/schema/class.go:95 (AddClass defaults + validation)
- vector index configs: entities/vectorindex/{hnsw,flat,dynamic}/config.go
- sharding config: usecases/sharding/config.go (shard count fixed at
  creation)
- multi-tenancy: one shard per tenant (sharding/state.go:293)
- replication: usecases/replica/config.go (factor, consistency levels)
- inverted index config: BM25 k1/b, stopwords (entities/models +
  inverted/stopwords)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field, asdict


class DataType:
    TEXT = "text"
    TEXT_ARRAY = "text[]"
    INT = "int"
    INT_ARRAY = "int[]"
    NUMBER = "number"
    NUMBER_ARRAY = "number[]"
    BOOL = "boolean"
    BOOL_ARRAY = "boolean[]"
    DATE = "date"
    DATE_ARRAY = "date[]"
    UUID = "uuid"
    UUID_ARRAY = "uuid[]"
    GEO = "geoCoordinates"
    BLOB = "blob"
    OBJECT = "object"
    REFERENCE = "cref"

    ALL = {TEXT, TEXT_ARRAY, INT, INT_ARRAY, NUMBER, NUMBER_ARRAY, BOOL,
           BOOL_ARRAY, DATE, DATE_ARRAY, UUID, UUID_ARRAY, GEO, BLOB, OBJECT,
           REFERENCE}


_NAME_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_]*$")


@dataclass
class Property:
    name: str
    data_type: str = DataType.TEXT
    tokenization: str = "word"  # word | lowercase | whitespace | field
    index_filterable: bool = True
    index_searchable: bool = True  # only meaningful for text
    description: str = ""
    nested: list["Property"] | None = None

    def validate(self):
        if not _NAME_RE.match(self.name):
            raise ValueError(f"invalid property name {self.name!r}")
        if self.data_type not in DataType.ALL:
            raise ValueError(f"unknown data type {self.data_type!r} for {self.name}")
        if self.tokenization not in ("word", "lowercase", "whitespace", "field"):
            raise ValueError(f"unknown tokenization {self.tokenization!r}")


@dataclass
class VectorIndexConfig:
    index_type: str = "flat"  # flat | hnsw | dynamic | noop (reference set + ivf)
    metric: str = "l2-squared"
    storage_dtype: str = "float32"  # float32 | bfloat16
    # quantization
    quantization: str | None = None  # None | pq | bq
    pq_segments: int | None = None
    # TPU-first default: 16 centroids = 4-bit codes whose ADC lookup is one
    # MXU matmul (ops/pallas_kernels.pq4_lut_block); 256 selects the
    # reference-style 8-bit codebook (reconstruct-matmul scan)
    pq_centroids: int = 16
    rescore_limit: int = 16
    # two-stage scan: width (bits, 128/256) of the separately-stored
    # transposed sign prefix — the capacity-regime operating point
    # (BASELINE r5: 10M×768 PQ 7.9 ms @ B=64 vs 30.5 exhaustive);
    # ignored for mesh-sharded stores and dims the prefix cannot cover
    prefix_bits: int | None = None
    # hnsw-ish knobs (used by graph/ivf indexes)
    ef: int = -1
    ef_construction: int = 128
    max_connections: int = 32
    # dynamic index upgrade threshold (dynamic/index.go:348)
    flat_to_ann_threshold: int = 10_000
    # ivf
    ivf_nlist: int = 0  # 0 = auto
    ivf_nprobe: int = 0  # 0 = auto
    # epoch-stacked device corpus (engine/epochs.py): seal the active
    # epoch every N rows; sealed epochs are immutable, compact in the
    # background (deletes reclaim HBM) and can migrate under memory
    # pressure. 0 = legacy single donated buffer. Flat indexes only —
    # graph/ivf layouts have their own reorganize stories.
    epoch_rows: int = 0

    def validate(self):
        from weaviate_tpu.ops.distances import DISTANCE_METRICS

        if self.index_type not in ("flat", "hnsw", "dynamic", "noop", "ivf"):
            raise ValueError(f"unknown vector index type {self.index_type!r}")
        if self.metric not in DISTANCE_METRICS:
            raise ValueError(f"unknown distance metric {self.metric!r}")
        if self.quantization not in (None, "pq", "bq"):
            raise ValueError(f"unknown quantization {self.quantization!r}")
        if self.prefix_bits is not None:
            if not isinstance(self.prefix_bits, int) \
                    or self.prefix_bits not in (128, 256):
                raise ValueError(
                    f"prefix_bits must be 128 or 256, got "
                    f"{self.prefix_bits!r}")
            if self.quantization is None:
                raise ValueError(
                    "prefix_bits requires quantization pq or bq")
        if self.epoch_rows:
            if not isinstance(self.epoch_rows, int) or self.epoch_rows < 0:
                raise ValueError(
                    f"epoch_rows must be a non-negative int, got "
                    f"{self.epoch_rows!r}")
            if self.index_type != "flat":
                raise ValueError(
                    "epoch_rows requires index_type 'flat' (graph/ivf "
                    "layouts have their own reorganize stories)")


@dataclass
class VectorConfig:
    """One named vector space (reference: hasTargetVectors, shard.go:130)."""

    name: str = ""  # "" = default/legacy single vector
    dim: int = 0  # 0 = inferred from first insert
    index: VectorIndexConfig = field(default_factory=VectorIndexConfig)
    vectorizer: str = "none"  # module name, or "none" = client provides
    # per-module settings (reference: moduleConfig per class/vector —
    # e.g. {"vectorizeClassName": false, "properties": [...]})
    module_config: dict = field(default_factory=dict)


@dataclass
class ShardingConfig:
    desired_count: int = 1
    virtual_per_physical: int = 128


@dataclass
class MultiTenancyConfig:
    enabled: bool = False
    auto_tenant_creation: bool = False
    auto_tenant_activation: bool = False


@dataclass
class ReplicationConfig:
    factor: int = 1
    async_enabled: bool = False


@dataclass
class InvertedIndexConfig:
    bm25_k1: float = 1.2
    bm25_b: float = 0.75
    stopwords_preset: str = "en"  # en | none
    stopwords_additions: list[str] = field(default_factory=list)
    stopwords_removals: list[str] = field(default_factory=list)
    index_timestamps: bool = False
    index_null_state: bool = False
    index_property_length: bool = False


@dataclass
class CollectionConfig:
    name: str
    description: str = ""
    properties: list[Property] = field(default_factory=list)
    vectors: list[VectorConfig] = field(default_factory=lambda: [VectorConfig()])
    sharding: ShardingConfig = field(default_factory=ShardingConfig)
    multi_tenancy: MultiTenancyConfig = field(default_factory=MultiTenancyConfig)
    replication: ReplicationConfig = field(default_factory=ReplicationConfig)
    inverted: InvertedIndexConfig = field(default_factory=InvertedIndexConfig)
    # class-level module settings keyed by module name (reference:
    # models.Class.ModuleConfig) — generative-*, reranker-* live here
    module_config: dict = field(default_factory=dict)

    def validate(self):
        if not _NAME_RE.match(self.name) or not self.name[0].isupper():
            raise ValueError(
                f"invalid collection name {self.name!r} (GraphQL-compatible "
                "UpperCamelCase required)"
            )
        seen = set()
        for p in self.properties:
            p.validate()
            if p.name.lower() in seen:
                raise ValueError(f"duplicate property {p.name!r}")
            seen.add(p.name.lower())
        vec_names = set()
        for v in self.vectors:
            v.index.validate()
            if v.name in vec_names:
                raise ValueError(f"duplicate vector name {v.name!r}")
            vec_names.add(v.name)
        if self.sharding.desired_count < 1:
            raise ValueError("shard count must be >= 1")
        if self.replication.factor < 1:
            raise ValueError("replication factor must be >= 1")

    def property(self, name: str) -> Property | None:
        for p in self.properties:
            if p.name == name:
                return p
        return None

    def vector_config(self, name: str = "") -> VectorConfig | None:
        for v in self.vectors:
            if v.name == name:
                return v
        return None

    # -- serialization -------------------------------------------------------

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "CollectionConfig":
        d = dict(d)
        d["properties"] = [
            Property(**{**p, "nested": None}) if not p.get("nested")
            else Property(**{**p, "nested": [Property(**n) for n in p["nested"]]})
            for p in d.get("properties", [])
        ]
        d["vectors"] = [
            VectorConfig(
                name=v.get("name", ""),
                dim=v.get("dim", 0),
                index=VectorIndexConfig(**v.get("index", {})),
                vectorizer=v.get("vectorizer", "none"),
                module_config=v.get("module_config", {}),
            )
            for v in d.get("vectors", [{}])
        ]
        d["sharding"] = ShardingConfig(**d.get("sharding", {}))
        d["multi_tenancy"] = MultiTenancyConfig(**d.get("multi_tenancy", {}))
        d["replication"] = ReplicationConfig(**d.get("replication", {}))
        d["inverted"] = InvertedIndexConfig(**d.get("inverted", {}))
        return cls(**d)
