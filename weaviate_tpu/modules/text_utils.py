"""Object -> corpus-text assembly shared by all text2vec modules.

Reference: usecases/modulecomponents/vectorizer/object_texts.go — class name
(camelCase split, lowered) + per-property values for indexed text
properties, optionally prefixed with the (lowered) property name; property
order is sorted for determinism.

Module-config keys honored (same names as the reference class settings):
  vectorizeClassName (default True), properties (allow-list),
  skippedProperties, vectorizePropertyName (default False).
"""

from __future__ import annotations

import re

_CAMEL = re.compile(r"[A-Z]?[a-z0-9]+|[A-Z]+(?![a-z])")


def camel_to_lower(name: str) -> str:
    return " ".join(m.group(0).lower() for m in _CAMEL.finditer(name))


def _text_values(value) -> list[str]:
    if isinstance(value, str):
        return [value.lower()]
    if isinstance(value, (list, tuple)):
        return [v.lower() for v in value if isinstance(v, str)]
    return []


def object_corpus(class_name: str, properties: dict, config: dict,
                  searchable_props: set[str] | None = None) -> str:
    """Build the text that represents one object to the embedder."""
    corpus: list[str] = []
    if config.get("vectorizeClassName", True):
        corpus.append(camel_to_lower(class_name))
    allow = set(config["properties"]) if config.get("properties") else None
    skip = set(config.get("skippedProperties", []))
    for prop_name in sorted(properties):
        if allow is not None and prop_name not in allow:
            continue
        if prop_name in skip:
            continue
        if searchable_props is not None and prop_name not in searchable_props:
            continue
        values = _text_values(properties[prop_name])
        if not values:
            continue
        if config.get("vectorizePropertyName", False):
            lower = camel_to_lower(prop_name)
            values = [f"{lower} {v}" for v in values]
        corpus.extend(values)
    return " ".join(corpus)
