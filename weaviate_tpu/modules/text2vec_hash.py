"""text2vec-hash: self-contained deterministic text embedder.

The reference ships one vectorizer that needs no external model service:
text2vec-bigram (modules/text2vec-bigram/vectorizer/vectorizer.go builds
vectors from character-bigram statistics). This is our analog: signed
feature hashing of word unigrams/bigrams and character trigrams onto a
fixed-dim unit sphere. Deterministic, dependency-free, and
similarity-preserving (cosine of hashed vectors approximates Jaccard-ish
token overlap), so nearText / hybrid / moves work end-to-end without a
model sidecar — the same role bigram plays in the reference's test stack.
"""

from __future__ import annotations

import hashlib
import re

import numpy as np

from weaviate_tpu.modules.base import TextVectorizer

_WORD = re.compile(r"[a-z0-9]+")


def _features(text: str) -> list[str]:
    words = _WORD.findall(text.lower())
    feats = list(words)
    feats.extend(f"{a}_{b}" for a, b in zip(words, words[1:]))
    for w in words:
        padded = f"^{w}$"
        feats.extend(padded[i:i + 3] for i in range(len(padded) - 2))
    return feats


def _hash(feature: str, seed: int) -> int:
    h = hashlib.blake2b(feature.encode(), digest_size=8,
                        salt=seed.to_bytes(8, "little")).digest()
    return int.from_bytes(h, "little")


class HashVectorizer(TextVectorizer):
    name = "text2vec-hash"

    def __init__(self, dim: int = 256, seed: int = 0):
        self.dim = dim
        self.seed = seed

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        dim = int(config.get("dimensions", self.dim))
        out = np.zeros((len(texts), dim), dtype=np.float32)
        for i, text in enumerate(texts):
            for feat in _features(text):
                h = _hash(feat, self.seed)
                idx = h % dim
                sign = 1.0 if (h >> 63) & 1 else -1.0
                out[i, idx] += sign
            norm = np.linalg.norm(out[i])
            if norm > 0:
                out[i] /= norm
        return out
