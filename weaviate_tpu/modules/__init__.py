"""Module ecosystem (reference: usecases/modules provider + modules/*).

``default_provider(db)`` registers the self-contained modules plus every
HTTP-client module, mirroring registerModules at configure_api.go:158 —
enable-list via the ENABLE_MODULES env var handled by the config layer.
"""

from weaviate_tpu.modules.base import (
    BackupBackend,
    Generative,
    MediaVectorizer,
    Module,
    ModuleError,
    Reranker,
    TextVectorizer,
)
from weaviate_tpu.modules.provider import Provider, RefVectorizer
from weaviate_tpu.modules.text2vec_hash import HashVectorizer


def default_provider(db=None, enabled: list[str] | None = None) -> Provider:
    from weaviate_tpu.modules import backup_backends as bb
    from weaviate_tpu.modules import http_modules as hm
    from weaviate_tpu.modules import http_modules_extra as hx

    provider = Provider(db)
    mods = [
        HashVectorizer(),
        RefVectorizer(),
        # text2vec
        hm.TransformersVectorizer(),
        hm.OpenAIVectorizer(),
        hm.CohereVectorizer(),
        hm.HuggingFaceVectorizer(),
        hm.OllamaVectorizer(),
        hx.ContextionaryVectorizer(),
        hx.PalmVectorizer(),
        hx.AWSVectorizer(),
        hx.JinaAIVectorizer(),
        hx.VoyageAIVectorizer(),
        hx.OctoAIVectorizer(),
        hx.GPT4AllVectorizer(),
        hx.BigramVectorizer(),
        # multi2vec / img2vec
        hm.ClipVectorizer(),
        hx.BindVectorizer(),
        hx.PalmMultiVectorizer(),
        hx.Img2VecNeural(),
        # rerankers
        hm.TransformersReranker(),
        hm.CohereReranker(),
        hx.VoyageAIReranker(),
        # generative
        hm.OpenAIGenerative(),
        hm.OllamaGenerative(),
        hm.CohereGenerative(),
        hx.AnyscaleGenerative(),
        hx.MistralGenerative(),
        hx.OctoAIGenerative(),
        hx.PalmGenerative(),
        hx.AWSGenerative(),
        # readers
        hx.QnATransformers(),
        hx.QnAOpenAI(),
        hx.NERTransformers(),
        hx.SumTransformers(),
        hx.TextSpellCheck(),
        # backup backends
        bb.FilesystemBackend(),
        bb.S3Backend(),
        bb.GCSBackend(),
        bb.AzureBackend(),
    ]
    for mod in mods:
        if enabled is None or mod.name in enabled:
            provider.register(mod)
    return provider


__all__ = [
    "BackupBackend", "Generative", "HashVectorizer", "MediaVectorizer",
    "Module", "ModuleError", "Provider", "RefVectorizer", "Reranker",
    "TextVectorizer", "default_provider",
]
