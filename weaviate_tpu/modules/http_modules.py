"""HTTP-backed modules: model inference stays in external services.

Reference architecture: every text2vec/generative/reranker module is a thin
HTTP client to a model sidecar or vendor API (e.g.
modules/text2vec-transformers/clients/transformers.go:71 POSTs to the
sidecar's /vectors/; modules/text2vec-openai calls api.openai.com). The
TPU engine itself never blocks on model inference — same two-plane split
the north star keeps.

All clients use stdlib urllib (no extra deps); API keys come from env vars
named like the reference's (OPENAI_APIKEY, COHERE_APIKEY, ...) or from
module settings.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

import numpy as np

from weaviate_tpu.modules.base import (
    Generative,
    MediaVectorizer,
    ModuleError,
    Reranker,
    TextVectorizer,
)


def _post_json(url: str, payload: dict, headers: dict | None = None,
               timeout: float = 60.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        body = e.read().decode(errors="replace")[:500]
        raise ModuleError(f"{url} -> HTTP {e.code}: {body}") from e
    except urllib.error.URLError as e:
        raise ModuleError(f"{url} unreachable: {e.reason}") from e


def _api_key(settings: dict, env_var: str) -> str:
    key = settings.get("apiKey") or os.environ.get(env_var, "")
    if not key:
        raise ModuleError(f"missing API key ({env_var})")
    return key


class TransformersVectorizer(TextVectorizer):
    """text2vec-transformers sidecar client (clients/transformers.go:71).
    Sidecar endpoints: POST {origin}/vectors/ {"text": ...} ->
    {"vector": [...]}; separate passage/query origins supported like
    TRANSFORMERS_PASSAGE_INFERENCE_API / _QUERY_."""

    name = "text2vec-transformers"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        base = settings.get("inferenceUrl") or os.environ.get(
            "TRANSFORMERS_INFERENCE_API", "http://localhost:8000")
        self.passage_url = settings.get("passageInferenceUrl") or os.environ.get(
            "TRANSFORMERS_PASSAGE_INFERENCE_API", base)
        self.query_url = settings.get("queryInferenceUrl") or os.environ.get(
            "TRANSFORMERS_QUERY_INFERENCE_API", base)

    def _embed(self, origin: str, text: str, config: dict) -> np.ndarray:
        out = _post_json(f"{origin.rstrip('/')}/vectors",
                         {"text": text, "config": {
                             "pooling_strategy":
                                 config.get("poolingStrategy", "masked_mean")}})
        return np.asarray(out["vector"], dtype=np.float32)

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        return np.stack([self._embed(self.passage_url, t, config)
                         for t in texts])

    def vectorize_query(self, text: str, config: dict) -> np.ndarray:
        return self._embed(self.query_url, text, config)


class OpenAIVectorizer(TextVectorizer):
    """text2vec-openai (modules/text2vec-openai/clients)."""

    name = "text2vec-openai"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.base_url = (settings.get("baseURL")
                         or os.environ.get("OPENAI_BASE_URL")
                         or "https://api.openai.com").rstrip("/")
        self.settings = settings

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        key = _api_key({**self.settings, **config}, "OPENAI_APIKEY")
        model = config.get("model", "text-embedding-3-small")
        out = _post_json(f"{self.base_url}/v1/embeddings",
                         {"input": texts, "model": model},
                         {"Authorization": f"Bearer {key}"})
        data = sorted(out["data"], key=lambda d: d["index"])
        return np.asarray([d["embedding"] for d in data], dtype=np.float32)


class CohereVectorizer(TextVectorizer):
    """text2vec-cohere; uses input_type search_document/search_query."""

    name = "text2vec-cohere"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.base_url = (settings.get("baseURL")
                         or "https://api.cohere.ai").rstrip("/")
        self.settings = settings

    def _embed(self, texts: list[str], config: dict,
               input_type: str) -> np.ndarray:
        key = _api_key({**self.settings, **config}, "COHERE_APIKEY")
        out = _post_json(f"{self.base_url}/v1/embed",
                         {"texts": texts,
                          "model": config.get("model", "embed-english-v3.0"),
                          "input_type": input_type},
                         {"Authorization": f"Bearer {key}"})
        return np.asarray(out["embeddings"], dtype=np.float32)

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        return self._embed(texts, config, "search_document")

    def vectorize_query(self, text: str, config: dict) -> np.ndarray:
        return self._embed([text], config, "search_query")[0]


class HuggingFaceVectorizer(TextVectorizer):
    """text2vec-huggingface (inference API feature-extraction)."""

    name = "text2vec-huggingface"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.base_url = (settings.get("endpointURL")
                         or "https://api-inference.huggingface.co").rstrip("/")
        self.settings = settings

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        key = _api_key({**self.settings, **config}, "HUGGINGFACE_APIKEY")
        model = config.get("model", "sentence-transformers/all-MiniLM-L6-v2")
        out = _post_json(
            f"{self.base_url}/pipeline/feature-extraction/{model}",
            {"inputs": texts, "options": {"wait_for_model": True}},
            {"Authorization": f"Bearer {key}"})
        arr = np.asarray(out, dtype=np.float32)
        if arr.ndim == 3:  # token-level output: mean-pool
            arr = arr.mean(axis=1)
        return arr


class OllamaVectorizer(TextVectorizer):
    """text2vec-ollama (modules/text2vec-ollama): local model server."""

    name = "text2vec-ollama"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.base_url = (settings.get("apiEndpoint")
                         or "http://localhost:11434").rstrip("/")

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        out = _post_json(f"{self.base_url}/api/embed",
                         {"model": config.get("model", "nomic-embed-text"),
                          "input": texts})
        return np.asarray(out["embeddings"], dtype=np.float32)


class ClipVectorizer(MediaVectorizer):
    """multi2vec-clip sidecar client (modules/multi2vec-clip/clients):
    POST /vectorize {"texts": [...], "images": [b64...]} ->
    {"textVectors": [...], "imageVectors": [...]}."""

    name = "multi2vec-clip"
    media_kinds = ("image",)

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.base_url = (settings.get("inferenceUrl") or os.environ.get(
            "CLIP_INFERENCE_API", "http://localhost:8000")).rstrip("/")

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        out = _post_json(f"{self.base_url}/vectorize", {"texts": texts})
        return np.asarray(out["textVectors"], dtype=np.float32)

    def vectorize_media(self, kind: str, data_b64: str,
                        config: dict) -> np.ndarray:
        out = _post_json(f"{self.base_url}/vectorize",
                         {"images": [data_b64]})
        return np.asarray(out["imageVectors"][0], dtype=np.float32)


class TransformersReranker(Reranker):
    """reranker-transformers sidecar client: POST /rerank
    {"query", "documents"} -> {"scores": [{"document","score"}]}."""

    name = "reranker-transformers"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.base_url = (settings.get("inferenceUrl") or os.environ.get(
            "RERANKER_INFERENCE_API", "http://localhost:8000")).rstrip("/")

    def rerank(self, query: str, documents: list[str],
               config: dict) -> list[float]:
        out = _post_json(f"{self.base_url}/rerank",
                         {"query": query, "documents": documents})
        scores = out["scores"]
        if scores and isinstance(scores[0], dict):
            return [s["score"] for s in scores]
        return [float(s) for s in scores]


class CohereReranker(Reranker):
    name = "reranker-cohere"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.base_url = (settings.get("baseURL")
                         or "https://api.cohere.ai").rstrip("/")
        self.settings = settings

    def rerank(self, query: str, documents: list[str],
               config: dict) -> list[float]:
        key = _api_key({**self.settings, **config}, "COHERE_APIKEY")
        out = _post_json(f"{self.base_url}/v1/rerank",
                         {"query": query, "documents": documents,
                          "model": config.get("model", "rerank-english-v3.0")},
                         {"Authorization": f"Bearer {key}"})
        scores = [0.0] * len(documents)
        for r in out["results"]:
            scores[r["index"]] = r["relevance_score"]
        return scores


class OpenAIGenerative(Generative):
    name = "generative-openai"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.base_url = (settings.get("baseURL")
                         or os.environ.get("OPENAI_BASE_URL")
                         or "https://api.openai.com").rstrip("/")
        self.settings = settings

    def generate(self, prompt: str, config: dict) -> str:
        key = _api_key({**self.settings, **config}, "OPENAI_APIKEY")
        out = _post_json(f"{self.base_url}/v1/chat/completions",
                         {"model": config.get("model", "gpt-4o-mini"),
                          "messages": [{"role": "user", "content": prompt}],
                          "max_tokens": config.get("maxTokens", 1024)},
                         {"Authorization": f"Bearer {key}"})
        return out["choices"][0]["message"]["content"]


class OllamaGenerative(Generative):
    name = "generative-ollama"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.base_url = (settings.get("apiEndpoint")
                         or "http://localhost:11434").rstrip("/")

    def generate(self, prompt: str, config: dict) -> str:
        out = _post_json(f"{self.base_url}/api/generate",
                         {"model": config.get("model", "llama3"),
                          "prompt": prompt, "stream": False})
        return out["response"]


class CohereGenerative(Generative):
    name = "generative-cohere"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.base_url = (settings.get("baseURL")
                         or "https://api.cohere.ai").rstrip("/")
        self.settings = settings

    def generate(self, prompt: str, config: dict) -> str:
        key = _api_key({**self.settings, **config}, "COHERE_APIKEY")
        out = _post_json(f"{self.base_url}/v1/chat",
                         {"message": prompt,
                          "model": config.get("model", "command-r")},
                         {"Authorization": f"Bearer {key}"})
        return out["text"]
