"""Module capability interfaces.

Reference: entities/modulecapabilities/*.go — a module declares capabilities
(Vectorizer, Searcher, AdditionalProperties, BackupBackend, ...) and the
provider dispatches on them (usecases/modules/modules.go:40). Here a module
subclasses the capability base matching what it provides; the provider
dispatches on isinstance.
"""

from __future__ import annotations

import numpy as np


class ModuleError(Exception):
    pass


class Module:
    """Base for all modules. ``name`` is the registry key used in
    VectorConfig.vectorizer / CollectionConfig.module_config."""

    name: str = ""

    def init(self, settings: dict | None = None) -> None:
        """Startup hook (reference: module Init at configure_api.go:403)."""

    def meta(self) -> dict:
        return {"name": self.name}


class TextVectorizer(Module):
    """text2vec-* capability (reference: modulecapabilities/vectorizer.go)."""

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        """Embed a batch of corpus texts -> [n, dim] float32."""
        raise NotImplementedError

    def vectorize_query(self, text: str, config: dict) -> np.ndarray:
        """Embed one query text; defaults to the corpus path (some APIs use
        a dedicated query model / input_type)."""
        return self.vectorize([text], config)[0]


class MediaVectorizer(Module):
    """multi2vec-* capability: embeds text and base64 media into one space."""

    media_kinds: tuple[str, ...] = ()

    def vectorize_media(self, kind: str, data_b64: str,
                        config: dict) -> np.ndarray:
        raise NotImplementedError

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        raise NotImplementedError

    def vectorize_query(self, text: str, config: dict) -> np.ndarray:
        return self.vectorize([text], config)[0]


class Reranker(Module):
    """reranker-* capability (reference: modules/reranker-*)."""

    def rerank(self, query: str, documents: list[str],
               config: dict) -> list[float]:
        raise NotImplementedError


class Generative(Module):
    """generative-* capability (reference: modules/generative-*)."""

    def generate(self, prompt: str, config: dict) -> str:
        raise NotImplementedError


class BackupBackend(Module):
    """backup-* capability (reference: modulecapabilities/backup.go:
    PutObject/GetObject/Initialize/HomeDir...)."""

    def put_file(self, backup_id: str, key: str, src_path: str) -> None:
        """Streamed upload; default buffers (override to stream)."""
        with open(src_path, "rb") as f:
            self.put(backup_id, key, f.read())

    def get_file(self, backup_id: str, key: str, dst_path: str) -> None:
        """Streamed download; default buffers (override to stream)."""
        import os

        data = self.get(backup_id, key)
        os.makedirs(os.path.dirname(dst_path), exist_ok=True)
        with open(dst_path, "wb") as f:
            f.write(data)

    def initialize(self, backup_id: str) -> None:
        raise NotImplementedError

    def put(self, backup_id: str, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, backup_id: str, key: str) -> bytes:
        raise NotImplementedError

    def list(self, backup_id: str) -> list[str]:
        raise NotImplementedError

    def home_dir(self, backup_id: str) -> str:
        raise NotImplementedError


class QnA(Module):
    """qna-* capability (reference: modules/qna-{openai,transformers} —
    extractive question answering over a result's text)."""

    def answer(self, text: str, question: str, config: dict) -> dict:
        """-> {"answer": str|None, "certainty": float|None,
        "startPosition": int, "endPosition": int, "hasAnswer": bool}"""
        raise NotImplementedError


class NER(Module):
    """ner-transformers capability: token classification over a text."""

    def recognize(self, text: str, config: dict) -> list[dict]:
        """-> [{"entity", "word", "certainty", "startPosition",
        "endPosition"}]"""
        raise NotImplementedError


class Summarizer(Module):
    """sum-transformers capability: abstractive summaries of a text."""

    def summarize(self, text: str, config: dict) -> list[dict]:
        """-> [{"property", "result"}]"""
        raise NotImplementedError


class SpellCheck(Module):
    """text-spellcheck capability: check/correct query text."""

    def check(self, text: str, config: dict) -> dict:
        """-> {"originalText", "correctedText", "didYouMean",
        "numberOfCorrections"}"""
        raise NotImplementedError
