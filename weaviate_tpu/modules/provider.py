"""Module provider: registry + dispatch during import and query.

Reference: usecases/modules/modules.go:40 (Provider), vectorizer dispatch
usecases/modules/vectorizer.go, nearText move semantics
usecases/modulecomponents/arguments/nearText/searcher_movements.go
(MoveTo: out = src*(1-w/2) + tgt*(w/2); MoveAwayFrom:
out = src + (w/2)*(src-tgt)).
"""

from __future__ import annotations

import re

import numpy as np

from weaviate_tpu.modules.base import (
    BackupBackend,
    Generative,
    MediaVectorizer,
    ModuleError,
    Module,
    Reranker,
    TextVectorizer,
)
from weaviate_tpu.modules.text_utils import object_corpus

_PROMPT_VAR = re.compile(r"\{([A-Za-z_][A-Za-z0-9_]*)\}")


class Provider:
    """``db``: optional Database handle — needed only by modules that read
    other objects (ref2vec-centroid resolves referenced objects' vectors)."""

    def __init__(self, db=None):
        self.db = db
        self._modules: dict[str, Module] = {}

    def register(self, module: Module, settings: dict | None = None) -> "Provider":
        module.init(settings or {})
        if hasattr(module, "attach_db"):
            module.attach_db(self.db)
        self._modules[module.name] = module
        return self

    def get(self, name: str) -> Module | None:
        return self._modules.get(name)

    def names(self) -> list[str]:
        return sorted(self._modules)

    def meta(self) -> dict:
        return {name: mod.meta() for name, mod in sorted(self._modules.items())}

    # -- vectorize at import (usecases/modules/vectorizer.go) ----------------

    def vectorizer_for(self, config, vec_name: str = ""):
        vc = config.vector_config(vec_name)
        if vc is None or vc.vectorizer in ("", "none"):
            return None
        mod = self._modules.get(vc.vectorizer)
        if mod is None:
            raise ModuleError(f"vectorizer module {vc.vectorizer!r} of class "
                              f"{config.name} is not enabled")
        return mod

    def vectorize_batch(self, config, specs: list[dict]) -> None:
        """Fill missing vectors in batch-import specs, one batched embed
        call per named vector space (reference: BatchVectorizer)."""
        searchable = {p.name for p in config.properties
                      if p.data_type in ("text", "text[]")}
        for vc in config.vectors:
            if vc.vectorizer in ("", "none"):
                continue
            todo = []
            for spec in specs:
                if vc.name:
                    has = vc.name in (spec.get("vectors") or {})
                else:
                    has = spec.get("vector") is not None
                if not has:
                    todo.append(spec)
            if not todo:
                # every object supplied its own vector: no server-side
                # vectorization needed, so an unregistered module is fine
                continue
            mod = self.vectorizer_for(config, vc.name)
            if isinstance(mod, RefVectorizer):
                for spec in todo:
                    vec = mod.centroid(config, vc.module_config,
                                       spec.get("properties", {}))
                    if vec is not None:
                        self._store(spec, vc.name, vec)
                continue
            if isinstance(mod, MediaVectorizer):
                # multi2vec: combine text + blob-property embeddings per
                # object (reference: multi2vec-clip imageFields/textFields
                # weighted mean, modules/multi2vec-clip/vectorizer.go).
                # Unlike text2vec, the class name is NOT vectorized by
                # default — a constant text component would dilute every
                # media vector of the class toward the same point.
                mc = {"vectorizeClassName": False, **vc.module_config}
                blob_props = [p.name for p in config.properties
                              if p.data_type == "blob"]
                texts = [object_corpus(config.name,
                                       spec.get("properties", {}),
                                       mc, searchable)
                         for spec in todo]
                text_vecs: dict[int, np.ndarray] = {}
                nonempty = [i for i, t in enumerate(texts) if t.strip()]
                if nonempty:
                    # one batched sidecar call for all text components
                    embedded = mod.vectorize([texts[i] for i in nonempty],
                                             vc.module_config)
                    for i, v in zip(nonempty, embedded):
                        text_vecs[i] = np.asarray(v, dtype=np.float32)
                for idx, spec in enumerate(todo):
                    props = spec.get("properties", {})
                    parts = []
                    if idx in text_vecs:
                        parts.append(text_vecs[idx])
                    for pname in blob_props:
                        blob = props.get(pname)
                        if blob:
                            # blobs carry no media-type tag; embed with the
                            # module's primary kind (clip: "image")
                            parts.append(np.asarray(
                                mod.vectorize_media(mod.media_kinds[0],
                                                    blob, vc.module_config),
                                dtype=np.float32))
                    if parts:
                        self._store(spec, vc.name,
                                    np.mean(np.stack(parts), axis=0))
                continue
            texts = [object_corpus(config.name, spec.get("properties", {}),
                                   vc.module_config, searchable)
                     for spec in todo]
            vecs = mod.vectorize(texts, vc.module_config)
            for spec, vec in zip(todo, vecs):
                self._store(spec, vc.name, np.asarray(vec, dtype=np.float32))

    @staticmethod
    def _store(spec: dict, vec_name: str, vec: np.ndarray) -> None:
        if vec_name:
            if spec.get("vectors") is None:  # key may exist holding None
                spec["vectors"] = {}
            spec["vectors"][vec_name] = vec
        else:
            spec["vector"] = vec

    # -- query-time hooks ----------------------------------------------------

    def vectorize_query(self, config, text: str,
                        vec_name: str = "") -> np.ndarray:
        mod = self.vectorizer_for(config, vec_name)
        if mod is None:
            raise ModuleError(
                f"class {config.name} has no vectorizer module for "
                f"vector {vec_name!r}")
        vc = config.vector_config(vec_name)
        return np.asarray(mod.vectorize_query(text, vc.module_config),
                          dtype=np.float32)

    def vectorize_media(self, config, kind: str, data_b64: str,
                        vec_name: str = "") -> np.ndarray:
        mod = self.vectorizer_for(config, vec_name)
        if not isinstance(mod, MediaVectorizer) or \
                kind not in mod.media_kinds:
            raise ModuleError(f"class {config.name} has no multi2vec module "
                              f"supporting near{kind.capitalize()}")
        vc = config.vector_config(vec_name)
        return np.asarray(mod.vectorize_media(kind, data_b64,
                                              vc.module_config),
                          dtype=np.float32)

    def apply_moves(self, col, vec: np.ndarray, near_text,
                    vec_name: str = "") -> np.ndarray:
        """nearText moveTo/moveAwayFrom: targets are the centroid of the
        moved-to concepts and/or anchor objects, in the same (possibly
        named) vector space as the query itself."""
        vec = np.asarray(vec, dtype=np.float32)
        for which in ("move_to", "move_away"):
            if not near_text.HasField(which):
                continue
            move = getattr(near_text, which)
            targets = []
            for concept in move.concepts:
                targets.append(
                    self.vectorize_query(col.config, concept, vec_name))
            for uid in move.uuids:
                obj = col.get_object(uid)
                if obj is None:
                    continue
                anchor = (obj.vectors or {}).get(vec_name) if vec_name \
                    else obj.vector
                if anchor is not None:
                    targets.append(anchor)
            if not targets:
                continue
            target = np.mean(np.stack(targets), axis=0)
            w = float(move.force) * 0.5
            if which == "move_to":
                vec = vec * (1 - w) + target * w
            else:
                vec = vec + w * (vec - target)
        return vec

    def rerank(self, config, query: str, documents: list[str],
               module_name: str | None = None) -> list[float]:
        mod, settings = self._class_module(config, Reranker, "reranker-",
                                           module_name)
        return mod.rerank(query, documents, settings)

    def generate_single(self, config, prompt: str, props: dict,
                        module_name: str | None = None) -> str:
        """Single-result prompt: {propName} placeholders are replaced with
        the result's property values (reference: generative modules)."""
        mod, settings = self._class_module(config, Generative, "generative-",
                                           module_name)
        filled = _PROMPT_VAR.sub(
            lambda m: str(props.get(m.group(1), m.group(0))), prompt)
        return mod.generate(filled, settings)

    def generate_grouped(self, config, task: str, all_props: list[dict],
                         module_name: str | None = None) -> str:
        mod, settings = self._class_module(config, Generative, "generative-",
                                           module_name)
        import json

        prompt = f"{task}\n\n{json.dumps(all_props, default=str)}"
        return mod.generate(prompt, settings)

    def answer(self, config, text: str, question: str,
               module_name: str | None = None) -> dict:
        """qna-* extractive answer (reference: _additional{answer})."""
        from weaviate_tpu.modules.base import QnA

        mod, settings = self._class_module(config, QnA, "qna-", module_name)
        return mod.answer(text, question, settings)

    def ner(self, config, text: str,
            module_name: str | None = None) -> list[dict]:
        from weaviate_tpu.modules.base import NER

        mod, settings = self._class_module(config, NER, "ner-", module_name)
        return mod.recognize(text, settings)

    def summarize(self, config, text: str,
                  module_name: str | None = None) -> list[dict]:
        from weaviate_tpu.modules.base import Summarizer

        mod, settings = self._class_module(config, Summarizer, "sum-",
                                           module_name)
        return mod.summarize(text, settings)

    def spellcheck(self, config, text: str,
                   module_name: str | None = None) -> dict:
        from weaviate_tpu.modules.base import SpellCheck

        mod, settings = self._class_module(config, SpellCheck, "text-spell",
                                           module_name)
        return mod.check(text, settings)

    def backup_backend(self, name: str) -> BackupBackend:
        mod = self._modules.get(f"backup-{name}", self._modules.get(name))
        if not isinstance(mod, BackupBackend):
            raise ModuleError(f"backup backend {name!r} is not enabled")
        return mod

    def _class_module(self, config, kind, prefix: str,
                      module_name: str | None):
        """Resolve a generative/reranker module for a class: explicit name,
        else the class's module_config entry with the matching prefix."""
        if module_name is None:
            for key in config.module_config:
                if key.startswith(prefix) and key in self._modules:
                    module_name = key
                    break
        if module_name is None:
            # No class config: only a single registered module of this kind
            # is an unambiguous default. Never silently pick one of many —
            # that could route user data to an unintended external service.
            candidates = [key for key, mod in self._modules.items()
                          if isinstance(mod, kind)]
            if len(candidates) == 1:
                module_name = candidates[0]
        mod = self._modules.get(module_name) if module_name else None
        if not isinstance(mod, kind):
            raise ModuleError(
                f"class {config.name} has no {prefix.rstrip('-')} module "
                f"configured (set one in moduleConfig or pass an explicit "
                f"provider)")
        return mod, config.module_config.get(module_name, {})


def needs_vector(config, spec: dict) -> bool:
    """True if this import spec still requires server-side vectorization
    for any vectorizer-enabled vector space."""
    for vc in config.vectors:
        if vc.vectorizer in ("", "none"):
            continue
        if vc.name:
            if vc.name not in (spec.get("vectors") or {}):
                return True
        elif spec.get("vector") is None:
            return True
    return False


class RefVectorizer(Module):
    """ref2vec-centroid: the object's vector is the mean of the vectors of
    the objects it references (reference: modules/ref2vec-centroid —
    config: referenceProperties, method=mean)."""

    name = "ref2vec-centroid"

    def __init__(self):
        self.db = None

    def attach_db(self, db) -> None:
        self.db = db

    def centroid(self, config, module_config: dict,
                 properties: dict) -> np.ndarray | None:
        if self.db is None:
            raise ModuleError("ref2vec-centroid needs a database handle")
        ref_props = module_config.get("referenceProperties") or [
            p.name for p in config.properties if p.data_type == "cref"]
        vecs = []
        for prop in ref_props:
            for beacon in properties.get(prop) or []:
                uid, target = _parse_beacon(beacon)
                if uid is None:
                    continue
                for cname in ([target] if target else
                              self.db.list_collections()):
                    try:
                        obj = self.db.get_collection(cname).get_object(uid)
                    except KeyError:
                        continue
                    if obj is not None and obj.vector is not None:
                        vecs.append(obj.vector)
                        break
        if not vecs:
            return None
        return np.mean(np.stack(vecs), axis=0).astype(np.float32)


def _parse_beacon(ref) -> tuple[str | None, str | None]:
    """weaviate://localhost[/Class]/uuid -> (uuid, class|None)."""
    beacon = ref.get("beacon", "") if isinstance(ref, dict) else str(ref)
    parts = [p for p in beacon.split("/") if p]
    if len(parts) < 2:
        return None, None
    uid = parts[-1]
    target = parts[-2] if len(parts) >= 4 and parts[-2][0].isupper() else None
    return uid, target
