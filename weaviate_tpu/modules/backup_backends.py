"""Backup backend modules.

Reference: modules/backup-{filesystem,s3,gcs,azure} implementing
modulecapabilities.BackupBackend (entities/modulecapabilities/backup.go:
Initialize/PutObject/GetObject/HomeDir/...). The filesystem backend is
fully local (BACKUP_FILESYSTEM_PATH, modules/backup-filesystem/backend.go);
the cloud backends talk to object stores. Here, s3/gcs/azure speak the
shared minimal "HTTP object store" dialect (unauthenticated PUT/GET
against an endpoint, the shape a local minio/azurite/fake-gcs test
container accepts) and fail with a clear error when no endpoint is
configured — this environment has no network egress, so real cloud
authentication (SigV4 etc.) is intentionally out of scope.
"""

from __future__ import annotations

import os
import urllib.error
import urllib.request

from weaviate_tpu.modules.base import BackupBackend, ModuleError


def walk_files(root: str) -> list[str]:
    """Sorted relative paths of every file under ``root``."""
    out = []
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            out.append(os.path.relpath(os.path.join(dirpath, fn), root))
    return sorted(out)


class FilesystemBackend(BackupBackend):
    """backup-filesystem: objects under <path>/<backup_id>/<key>."""

    name = "backup-filesystem"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.root = settings.get("path") or os.environ.get(
            "BACKUP_FILESYSTEM_PATH", "")

    def _require_root(self) -> str:
        if not self.root:
            raise ModuleError(
                "backup-filesystem needs a path (module setting 'path' or "
                "BACKUP_FILESYSTEM_PATH)")
        return self.root

    def initialize(self, backup_id: str) -> None:
        os.makedirs(os.path.join(self._require_root(), backup_id),
                    exist_ok=True)

    def put(self, backup_id: str, key: str, data: bytes) -> None:
        path = self._safe_path(backup_id, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def get(self, backup_id: str, key: str) -> bytes:
        path = self._safe_path(backup_id, key)
        if not os.path.exists(path):
            raise KeyError(f"{backup_id}/{key} not found")
        with open(path, "rb") as f:
            return f.read()

    def list(self, backup_id: str) -> list[str]:
        return walk_files(os.path.join(self._require_root(), backup_id))

    def put_file(self, backup_id: str, key: str, src_path: str) -> None:
        """Streamed variant: never materializes the file in memory."""
        import shutil

        dst = self._safe_path(backup_id, key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = f"{dst}.tmp"
        with open(src_path, "rb") as src, open(tmp, "wb") as out:
            shutil.copyfileobj(src, out, 1 << 20)
        os.replace(tmp, dst)

    def get_file(self, backup_id: str, key: str, dst_path: str) -> None:
        import shutil

        src = self._safe_path(backup_id, key)
        if not os.path.exists(src):
            raise KeyError(f"{backup_id}/{key} not found")
        os.makedirs(os.path.dirname(dst_path), exist_ok=True)
        with open(src, "rb") as f, open(dst_path, "wb") as out:
            shutil.copyfileobj(f, out, 1 << 20)

    def home_dir(self, backup_id: str) -> str:
        return os.path.join(self._require_root(), backup_id)

    def _safe_path(self, backup_id: str, key: str) -> str:
        # containment is anchored at the CONFIGURED root, so a traversal
        # backup_id ('..') can't move the anchor outside it
        base = os.path.abspath(self._require_root())
        root = os.path.abspath(os.path.join(base, backup_id))
        if not root.startswith(base + os.sep):
            raise ModuleError(f"backup id {backup_id!r} escapes the "
                              "backup root")
        path = os.path.abspath(os.path.join(root, key))
        if not path.startswith(root + os.sep) and path != root:
            raise ModuleError(f"backup key {key!r} escapes the backup root")
        return path


class _HttpObjectStoreBackend(BackupBackend):
    """Shared minimal HTTP object-store client for the cloud backends:
    PUT/GET <endpoint>/<container>/<backup_id>/<key>."""

    endpoint_setting = "endpoint"
    endpoint_env = ""
    container_setting = "bucket"
    container_env = ""
    default_container = "weaviate-backups"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.endpoint = (settings.get(self.endpoint_setting)
                         or os.environ.get(self.endpoint_env, "")).rstrip("/")
        self.container = (settings.get(self.container_setting)
                          or os.environ.get(self.container_env, "")
                          or self.default_container)

    def _url(self, backup_id: str, key: str) -> str:
        if not self.endpoint:
            raise ModuleError(
                f"{self.name} needs an endpoint (module setting "
                f"{self.endpoint_setting!r} or {self.endpoint_env})")
        return f"{self.endpoint}/{self.container}/{backup_id}/{key}"

    def initialize(self, backup_id: str) -> None:
        self._url(backup_id, "")  # endpoint check

    def put(self, backup_id: str, key: str, data: bytes) -> None:
        req = urllib.request.Request(self._url(backup_id, key), data=data,
                                     method="PUT")
        with urllib.request.urlopen(req, timeout=60):
            pass

    def get(self, backup_id: str, key: str) -> bytes:
        try:
            with urllib.request.urlopen(self._url(backup_id, key),
                                        timeout=60) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise KeyError(f"{backup_id}/{key} not found")
            raise

    def put_file(self, backup_id: str, key: str, src_path: str) -> None:
        size = os.path.getsize(src_path)
        with open(src_path, "rb") as f:
            req = urllib.request.Request(
                self._url(backup_id, key), data=f, method="PUT",
                headers={"Content-Length": str(size)})
            with urllib.request.urlopen(req, timeout=300):
                pass

    def get_file(self, backup_id: str, key: str, dst_path: str) -> None:
        import shutil

        os.makedirs(os.path.dirname(dst_path), exist_ok=True)
        try:
            with urllib.request.urlopen(self._url(backup_id, key),
                                        timeout=300) as resp, \
                    open(dst_path, "wb") as out:
                shutil.copyfileobj(resp, out, 1 << 20)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise KeyError(f"{backup_id}/{key} not found")
            raise

    def list(self, backup_id: str) -> list[str]:
        raise ModuleError(f"{self.name} does not support listing without "
                          "cloud credentials")

    def home_dir(self, backup_id: str) -> str:
        return f"{self.endpoint}/{self.container}/{backup_id}" \
            if self.endpoint else ""


class S3Backend(_HttpObjectStoreBackend):
    name = "backup-s3"
    endpoint_env = "BACKUP_S3_ENDPOINT"
    container_env = "BACKUP_S3_BUCKET"


class GCSBackend(_HttpObjectStoreBackend):
    name = "backup-gcs"
    endpoint_env = "BACKUP_GCS_ENDPOINT"
    container_env = "BACKUP_GCS_BUCKET"


class AzureBackend(_HttpObjectStoreBackend):
    name = "backup-azure"
    endpoint_env = "BACKUP_AZURE_ENDPOINT"
    container_setting = "container"
    container_env = "BACKUP_AZURE_CONTAINER"
