"""Backup backend modules.

Reference: modules/backup-{filesystem,s3,gcs,azure} implementing
modulecapabilities.BackupBackend (entities/modulecapabilities/backup.go:
Initialize/PutObject/GetObject/HomeDir/...). The filesystem backend is
fully local (BACKUP_FILESYSTEM_PATH, modules/backup-filesystem/backend.go);
the cloud backends talk to object stores. Here, s3/gcs/azure speak the
shared minimal "HTTP object store" dialect (PUT/GET against an endpoint —
the shape a local minio/azurite/fake-gcs test container accepts) with
REAL cloud authentication layered on when credentials are configured:

- backup-s3:    AWS Signature V4 (AWS_ACCESS_KEY_ID/_SECRET_ACCESS_KEY,
                optional _SESSION_TOKEN; region from BACKUP_S3_REGION or
                AWS_REGION) — reference: modules/backup-s3 via minio-go.
- backup-gcs:   OAuth bearer token (GOOGLE_OAUTH_ACCESS_TOKEN or
                GCP_ACCESS_TOKEN) — reference: modules/backup-gcs.
- backup-azure: SAS token appended to every URL
                (AZURE_STORAGE_SAS_TOKEN) — reference: modules/backup-azure.

Unauthenticated endpoints (minio/azurite/fake-gcs in CI) keep working:
auth headers attach only when credentials are present.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request

from weaviate_tpu.modules.base import BackupBackend, ModuleError


def sigv4_headers(method: str, url: str, region: str, service: str,
                  access_key: str, secret_key: str, payload_hash: str,
                  amz_date: str, session_token: str | None = None,
                  extra_headers: dict | None = None) -> dict:
    """AWS Signature Version 4 request headers (no SDK — ~80 lines of
    canonicalization + HMAC chain per the SigV4 spec). Deterministic given
    ``amz_date``; tests/test_backup.py pins AWS's published known-answer
    vector. Reference: modules/backup-s3 (minio-go signs the same way)."""
    parts = urllib.parse.urlsplit(url)
    host = parts.netloc
    canonical_uri = urllib.parse.quote(parts.path or "/", safe="/")
    q = urllib.parse.parse_qsl(parts.query, keep_blank_values=True)
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}="
        f"{urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(q))
    headers = {"host": host, "x-amz-date": amz_date,
               "x-amz-content-sha256": payload_hash}
    if session_token:
        headers["x-amz-security-token"] = session_token
    for k, v in (extra_headers or {}).items():
        headers[k.lower()] = " ".join(str(v).split())
    signed = ";".join(sorted(headers))
    canonical_headers = "".join(
        f"{k}:{headers[k]}\n" for k in sorted(headers))
    canonical_request = "\n".join([
        method.upper(), canonical_uri, canonical_query,
        canonical_headers, signed, payload_hash])
    date = amz_date[:8]
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest()])

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + secret_key).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()
    auth = (f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={signature}")
    out = {k2: v for k2, v in headers.items() if k2 != "host"}
    out["Authorization"] = auth
    return out


def walk_files(root: str) -> list[str]:
    """Sorted relative paths of every file under ``root``."""
    out = []
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            out.append(os.path.relpath(os.path.join(dirpath, fn), root))
    return sorted(out)


class FilesystemBackend(BackupBackend):
    """backup-filesystem: objects under <path>/<backup_id>/<key>."""

    name = "backup-filesystem"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.root = settings.get("path") or os.environ.get(
            "BACKUP_FILESYSTEM_PATH", "")

    def _require_root(self) -> str:
        if not self.root:
            raise ModuleError(
                "backup-filesystem needs a path (module setting 'path' or "
                "BACKUP_FILESYSTEM_PATH)")
        return self.root

    def initialize(self, backup_id: str) -> None:
        os.makedirs(os.path.join(self._require_root(), backup_id),
                    exist_ok=True)

    def put(self, backup_id: str, key: str, data: bytes) -> None:
        path = self._safe_path(backup_id, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def get(self, backup_id: str, key: str) -> bytes:
        path = self._safe_path(backup_id, key)
        if not os.path.exists(path):
            raise KeyError(f"{backup_id}/{key} not found")
        with open(path, "rb") as f:
            return f.read()

    def list(self, backup_id: str) -> list[str]:
        return walk_files(os.path.join(self._require_root(), backup_id))

    def put_file(self, backup_id: str, key: str, src_path: str) -> None:
        """Streamed variant: never materializes the file in memory."""
        import shutil

        dst = self._safe_path(backup_id, key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = f"{dst}.tmp"
        with open(src_path, "rb") as src, open(tmp, "wb") as out:
            shutil.copyfileobj(src, out, 1 << 20)
        os.replace(tmp, dst)

    def get_file(self, backup_id: str, key: str, dst_path: str) -> None:
        import shutil

        src = self._safe_path(backup_id, key)
        if not os.path.exists(src):
            raise KeyError(f"{backup_id}/{key} not found")
        os.makedirs(os.path.dirname(dst_path), exist_ok=True)
        with open(src, "rb") as f, open(dst_path, "wb") as out:
            shutil.copyfileobj(f, out, 1 << 20)

    def home_dir(self, backup_id: str) -> str:
        return os.path.join(self._require_root(), backup_id)

    def _safe_path(self, backup_id: str, key: str) -> str:
        # containment is anchored at the CONFIGURED root, so a traversal
        # backup_id ('..') can't move the anchor outside it
        base = os.path.abspath(self._require_root())
        root = os.path.abspath(os.path.join(base, backup_id))
        if not root.startswith(base + os.sep):
            raise ModuleError(f"backup id {backup_id!r} escapes the "
                              "backup root")
        path = os.path.abspath(os.path.join(root, key))
        if not path.startswith(root + os.sep) and path != root:
            raise ModuleError(f"backup key {key!r} escapes the backup root")
        return path


class _HttpObjectStoreBackend(BackupBackend):
    """Shared minimal HTTP object-store client for the cloud backends:
    PUT/GET <endpoint>/<container>/<backup_id>/<key>."""

    endpoint_setting = "endpoint"
    endpoint_env = ""
    container_setting = "bucket"
    container_env = ""
    default_container = "weaviate-backups"

    def init(self, settings: dict | None = None) -> None:
        settings = settings or {}
        self.endpoint = (settings.get(self.endpoint_setting)
                         or os.environ.get(self.endpoint_env, "")).rstrip("/")
        self.container = (settings.get(self.container_setting)
                          or os.environ.get(self.container_env, "")
                          or self.default_container)

    def _url(self, backup_id: str, key: str) -> str:
        if not self.endpoint:
            raise ModuleError(
                f"{self.name} needs an endpoint (module setting "
                f"{self.endpoint_setting!r} or {self.endpoint_env})")
        return f"{self.endpoint}/{self.container}/{backup_id}/{key}"

    def _auth_headers(self, method: str, url: str,
                      payload_hash: str) -> dict:
        """Per-backend request authentication; {} = anonymous (the
        minio/azurite/fake-gcs CI shape)."""
        return {}

    def _sign_url(self, url: str) -> str:
        """Per-backend URL decoration (Azure SAS)."""
        return url

    def initialize(self, backup_id: str) -> None:
        self._url(backup_id, "")  # endpoint check

    _EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()

    def put(self, backup_id: str, key: str, data: bytes) -> None:
        url = self._sign_url(self._url(backup_id, key))
        headers = self._auth_headers(
            "PUT", url, hashlib.sha256(data).hexdigest())
        req = urllib.request.Request(url, data=data, method="PUT",
                                     headers=headers)
        with urllib.request.urlopen(req, timeout=60):
            pass

    def get(self, backup_id: str, key: str) -> bytes:
        url = self._sign_url(self._url(backup_id, key))
        req = urllib.request.Request(
            url, headers=self._auth_headers("GET", url, self._EMPTY_SHA256))
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise KeyError(f"{backup_id}/{key} not found")
            raise

    def put_file(self, backup_id: str, key: str, src_path: str) -> None:
        size = os.path.getsize(src_path)
        url = self._sign_url(self._url(backup_id, key))
        headers = {"Content-Length": str(size)}
        headers.update(self._auth_headers("PUT", url, "UNSIGNED-PAYLOAD"))
        with open(src_path, "rb") as f:
            req = urllib.request.Request(url, data=f, method="PUT",
                                         headers=headers)
            with urllib.request.urlopen(req, timeout=300):
                pass

    def get_file(self, backup_id: str, key: str, dst_path: str) -> None:
        import shutil

        os.makedirs(os.path.dirname(dst_path), exist_ok=True)
        url = self._sign_url(self._url(backup_id, key))
        req = urllib.request.Request(
            url, headers=self._auth_headers("GET", url, self._EMPTY_SHA256))
        try:
            with urllib.request.urlopen(req, timeout=300) as resp, \
                    open(dst_path, "wb") as out:
                shutil.copyfileobj(resp, out, 1 << 20)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise KeyError(f"{backup_id}/{key} not found")
            raise

    def list(self, backup_id: str) -> list[str]:
        raise ModuleError(f"{self.name} does not support listing without "
                          "cloud credentials")

    def home_dir(self, backup_id: str) -> str:
        return f"{self.endpoint}/{self.container}/{backup_id}" \
            if self.endpoint else ""


class S3Backend(_HttpObjectStoreBackend):
    name = "backup-s3"
    endpoint_env = "BACKUP_S3_ENDPOINT"
    container_env = "BACKUP_S3_BUCKET"

    def _auth_headers(self, method: str, url: str,
                      payload_hash: str) -> dict:
        access = os.environ.get("AWS_ACCESS_KEY_ID", "")
        secret = os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        if not access or not secret:
            return {}  # anonymous endpoint (minio CI shape)
        region = (os.environ.get("BACKUP_S3_REGION")
                  or os.environ.get("AWS_REGION") or "us-east-1")
        amz_date = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y%m%dT%H%M%SZ")
        return sigv4_headers(
            method, url, region, "s3", access, secret, payload_hash,
            amz_date,
            session_token=os.environ.get("AWS_SESSION_TOKEN") or None)


class GCSBackend(_HttpObjectStoreBackend):
    name = "backup-gcs"
    endpoint_env = "BACKUP_GCS_ENDPOINT"
    container_env = "BACKUP_GCS_BUCKET"

    def _auth_headers(self, method: str, url: str,
                      payload_hash: str) -> dict:
        token = (os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
                 or os.environ.get("GCP_ACCESS_TOKEN"))
        return {"Authorization": f"Bearer {token}"} if token else {}


class AzureBackend(_HttpObjectStoreBackend):
    name = "backup-azure"
    endpoint_env = "BACKUP_AZURE_ENDPOINT"
    container_setting = "container"
    container_env = "BACKUP_AZURE_CONTAINER"

    def _auth_headers(self, method: str, url: str,
                      payload_hash: str) -> dict:
        # blob uploads need the blob type even for azurite
        return {"x-ms-blob-type": "BlockBlob"} if method == "PUT" else {}

    def _sign_url(self, url: str) -> str:
        sas = os.environ.get("AZURE_STORAGE_SAS_TOKEN", "").lstrip("?")
        if not sas:
            return url
        sep = "&" if "?" in url else "?"
        return f"{url}{sep}{sas}"
