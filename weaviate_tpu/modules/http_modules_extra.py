"""Extended module roster: the rest of the reference's 38-module ecosystem.

Reference: modules/* — every entry is a thin HTTP client to a sidecar
container (contextionary, bind, img2vec-neural, qna/ner/sum-transformers,
gpt4all) or a vendor API (palm, aws, jinaai, voyageai, octoai, anyscale,
mistral). Module names, env-var names, and sidecar endpoint shapes follow
the reference so existing deployments' configuration carries over.
text2vec-bigram is self-contained (hashed character bigrams), like the
reference's dev-oriented bigram module.
"""

from __future__ import annotations

import os

import numpy as np

from weaviate_tpu.modules.base import (
    Generative,
    MediaVectorizer,
    ModuleError,
    NER,
    QnA,
    Reranker,
    SpellCheck,
    Summarizer,
    TextVectorizer,
)
from weaviate_tpu.modules.http_modules import _api_key, _post_json


def _origin(settings: dict, key: str, env_var: str, default: str) -> str:
    return (settings.get(key) or os.environ.get(env_var, default)).rstrip("/")


# ---- text2vec -------------------------------------------------------------


class ContextionaryVectorizer(TextVectorizer):
    """text2vec-contextionary sidecar (modules/text2vec-contextionary):
    POST {origin}/v1/vectorize {"text": ...} -> {"vector": [...]}."""

    name = "text2vec-contextionary"

    def init(self, settings: dict | None = None) -> None:
        self.base = _origin(settings or {}, "inferenceUrl",
                            "CONTEXTIONARY_URL", "http://localhost:9999")

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        return np.stack([
            np.asarray(_post_json(f"{self.base}/v1/vectorize",
                                  {"text": t})["vector"], dtype=np.float32)
            for t in texts])


class PalmVectorizer(TextVectorizer):
    """text2vec-palm (Google Vertex embeddings API)."""

    name = "text2vec-palm"

    def init(self, settings: dict | None = None) -> None:
        self.settings = settings or {}

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        cfg = {**self.settings, **config}
        project = cfg.get("projectId")
        model = cfg.get("modelId", "textembedding-gecko@001")
        if not project:
            raise ModuleError("text2vec-palm needs moduleConfig.projectId")
        key = _api_key(cfg, "PALM_APIKEY")
        base = cfg.get("apiEndpoint",
                       "https://us-central1-aiplatform.googleapis.com")
        url = (f"{base}/v1/projects/{project}/locations/us-central1/"
               f"publishers/google/models/{model}:predict")
        out = _post_json(url, {"instances": [{"content": t} for t in texts]},
                         headers={"Authorization": f"Bearer {key}"})
        return np.asarray(
            [p["embeddings"]["values"] for p in out["predictions"]],
            dtype=np.float32)


class AWSVectorizer(TextVectorizer):
    """text2vec-aws. Real Bedrock needs SigV4 request signing; this client
    targets a pre-signed/proxy endpoint (AWS_BEDROCK_ENDPOINT) the way
    test rigs front Bedrock, and errors clearly otherwise."""

    name = "text2vec-aws"

    def init(self, settings: dict | None = None) -> None:
        self.settings = settings or {}

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        cfg = {**self.settings, **config}
        endpoint = cfg.get("endpoint") or os.environ.get(
            "AWS_BEDROCK_ENDPOINT", "")
        if not endpoint:
            raise ModuleError(
                "text2vec-aws needs an endpoint (moduleConfig.endpoint or "
                "AWS_BEDROCK_ENDPOINT; direct Bedrock access requires "
                "SigV4 signing this build does not perform)")
        model = cfg.get("model", "amazon.titan-embed-text-v1")
        out = [
            _post_json(f"{endpoint.rstrip('/')}/model/{model}/invoke",
                       {"inputText": t})["embedding"]
            for t in texts
        ]
        return np.asarray(out, dtype=np.float32)


class _SimpleEmbedAPI(TextVectorizer):
    """Shared shape: POST {base}/embeddings {model, input} ->
    {"data": [{"embedding": [...]}, ...]} (openai-compatible vendors)."""

    base_url = ""
    env_key = ""
    default_model = ""

    def init(self, settings: dict | None = None) -> None:
        self.settings = settings or {}

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        cfg = {**self.settings, **config}
        key = _api_key(cfg, self.env_key)
        base = (cfg.get("baseURL") or self.base_url).rstrip("/")
        out = _post_json(f"{base}/embeddings",
                         {"model": cfg.get("model", self.default_model),
                          "input": texts},
                         headers={"Authorization": f"Bearer {key}"})
        return np.asarray([d["embedding"] for d in out["data"]],
                          dtype=np.float32)


class JinaAIVectorizer(_SimpleEmbedAPI):
    name = "text2vec-jinaai"
    base_url = "https://api.jina.ai/v1"
    env_key = "JINAAI_APIKEY"
    default_model = "jina-embeddings-v2-base-en"


class VoyageAIVectorizer(_SimpleEmbedAPI):
    name = "text2vec-voyageai"
    base_url = "https://api.voyageai.com/v1"
    env_key = "VOYAGEAI_APIKEY"
    default_model = "voyage-2"


class OctoAIVectorizer(_SimpleEmbedAPI):
    name = "text2vec-octoai"
    base_url = "https://text.octoai.run/v1"
    env_key = "OCTOAI_APIKEY"
    default_model = "thenlper/gte-large"


class GPT4AllVectorizer(TextVectorizer):
    """text2vec-gpt4all sidecar: POST {origin}/vectorize {"text": ...}."""

    name = "text2vec-gpt4all"

    def init(self, settings: dict | None = None) -> None:
        self.base = _origin(settings or {}, "inferenceUrl",
                            "GPT4ALL_INFERENCE_API", "http://localhost:8000")

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        return np.stack([
            np.asarray(_post_json(f"{self.base}/vectorize",
                                  {"text": t})["vector"], dtype=np.float32)
            for t in texts])


class BigramVectorizer(TextVectorizer):
    """text2vec-bigram: self-contained hashed character-bigram embedding
    (reference: modules/text2vec-bigram, a dependency-free dev module)."""

    name = "text2vec-bigram"

    def init(self, settings: dict | None = None) -> None:
        self.dim = int((settings or {}).get("dim", 256))

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        dim = int(config.get("dim", self.dim))
        out = np.zeros((len(texts), dim), dtype=np.float32)
        for i, text in enumerate(texts):
            t = text.lower()
            for a, b in zip(t, t[1:]):
                out[i, (ord(a) * 31 + ord(b)) % dim] += 1.0
            n = np.linalg.norm(out[i])
            if n > 0:
                out[i] /= n
        return out


# ---- multi2vec / img2vec --------------------------------------------------


class BindVectorizer(MediaVectorizer):
    """multi2vec-bind sidecar (ImageBind): one embedding space for text,
    image, audio, video (modules/multi2vec-bind/clients)."""

    name = "multi2vec-bind"
    media_kinds = ("image", "audio", "video", "thermal", "depth", "imu")

    def init(self, settings: dict | None = None) -> None:
        self.base = _origin(settings or {}, "inferenceUrl",
                            "BIND_INFERENCE_API", "http://localhost:8000")

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        out = _post_json(f"{self.base}/vectorize", {"texts": texts})
        return np.asarray(out["textVectors"], dtype=np.float32)

    def vectorize_media(self, kind: str, data_b64: str,
                        config: dict) -> np.ndarray:
        out = _post_json(f"{self.base}/vectorize", {f"{kind}s": [data_b64]})
        return np.asarray(out[f"{kind}Vectors"][0], dtype=np.float32)


class PalmMultiVectorizer(MediaVectorizer):
    """multi2vec-palm (Vertex multimodal embeddings)."""

    name = "multi2vec-palm"
    media_kinds = ("image", "video")

    def init(self, settings: dict | None = None) -> None:
        self.settings = settings or {}

    def _predict(self, instance: dict, config: dict) -> dict:
        cfg = {**self.settings, **config}
        project = cfg.get("projectId")
        if not project:
            raise ModuleError("multi2vec-palm needs moduleConfig.projectId")
        key = _api_key(cfg, "PALM_APIKEY")
        base = cfg.get("apiEndpoint",
                       "https://us-central1-aiplatform.googleapis.com")
        model = cfg.get("modelId", "multimodalembedding@001")
        url = (f"{base}/v1/projects/{project}/locations/us-central1/"
               f"publishers/google/models/{model}:predict")
        out = _post_json(url, {"instances": [instance]},
                         headers={"Authorization": f"Bearer {key}"})
        return out["predictions"][0]

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        return np.stack([
            np.asarray(self._predict({"text": t}, config)["textEmbedding"],
                       dtype=np.float32) for t in texts])

    def vectorize_media(self, kind: str, data_b64: str,
                        config: dict) -> np.ndarray:
        key = {"image": ("image", "imageEmbedding"),
               "video": ("video", "videoEmbedding")}[kind]
        pred = self._predict({key[0]: {"bytesBase64Encoded": data_b64}},
                             config)
        return np.asarray(pred[key[1]], dtype=np.float32)


class Img2VecNeural(MediaVectorizer):
    """img2vec-neural sidecar: POST {origin}/vectors {"image": b64} ->
    {"vector": [...]} (modules/img2vec-neural/clients)."""

    name = "img2vec-neural"
    media_kinds = ("image",)

    def init(self, settings: dict | None = None) -> None:
        self.base = _origin(settings or {}, "inferenceUrl",
                            "IMAGE_INFERENCE_API", "http://localhost:8000")

    def vectorize(self, texts: list[str], config: dict) -> np.ndarray:
        raise ModuleError("img2vec-neural embeds images only")

    def vectorize_media(self, kind: str, data_b64: str,
                        config: dict) -> np.ndarray:
        out = _post_json(f"{self.base}/vectors", {"image": data_b64})
        return np.asarray(out["vector"], dtype=np.float32)


# ---- generative -----------------------------------------------------------


class _OpenAICompatGenerative(Generative):
    """POST {base}/chat/completions, openai wire shape."""

    base_url = ""
    env_key = ""
    default_model = ""

    def init(self, settings: dict | None = None) -> None:
        self.settings = settings or {}

    def generate(self, prompt: str, config: dict) -> str:
        cfg = {**self.settings, **config}
        key = _api_key(cfg, self.env_key)
        base = (cfg.get("baseURL") or self.base_url).rstrip("/")
        out = _post_json(
            f"{base}/chat/completions",
            {"model": cfg.get("model", self.default_model),
             "messages": [{"role": "user", "content": prompt}],
             "max_tokens": cfg.get("maxTokens", 1024)},
            headers={"Authorization": f"Bearer {key}"})
        return out["choices"][0]["message"]["content"]


class AnyscaleGenerative(_OpenAICompatGenerative):
    name = "generative-anyscale"
    base_url = "https://api.endpoints.anyscale.com/v1"
    env_key = "ANYSCALE_APIKEY"
    default_model = "meta-llama/Llama-2-70b-chat-hf"


class MistralGenerative(_OpenAICompatGenerative):
    name = "generative-mistral"
    base_url = "https://api.mistral.ai/v1"
    env_key = "MISTRAL_APIKEY"
    default_model = "open-mistral-7b"


class OctoAIGenerative(_OpenAICompatGenerative):
    name = "generative-octoai"
    base_url = "https://text.octoai.run/v1"
    env_key = "OCTOAI_APIKEY"
    default_model = "meta-llama-3-8b-instruct"


class PalmGenerative(Generative):
    name = "generative-palm"

    def init(self, settings: dict | None = None) -> None:
        self.settings = settings or {}

    def generate(self, prompt: str, config: dict) -> str:
        cfg = {**self.settings, **config}
        project = cfg.get("projectId")
        if not project:
            raise ModuleError("generative-palm needs moduleConfig.projectId")
        key = _api_key(cfg, "PALM_APIKEY")
        base = cfg.get("apiEndpoint",
                       "https://us-central1-aiplatform.googleapis.com")
        model = cfg.get("modelId", "chat-bison")
        url = (f"{base}/v1/projects/{project}/locations/us-central1/"
               f"publishers/google/models/{model}:predict")
        out = _post_json(
            url, {"instances": [{"messages": [
                {"author": "user", "content": prompt}]}]},
            headers={"Authorization": f"Bearer {key}"})
        return out["predictions"][0]["candidates"][0]["content"]


class AWSGenerative(Generative):
    name = "generative-aws"

    def init(self, settings: dict | None = None) -> None:
        self.settings = settings or {}

    def generate(self, prompt: str, config: dict) -> str:
        cfg = {**self.settings, **config}
        endpoint = cfg.get("endpoint") or os.environ.get(
            "AWS_BEDROCK_ENDPOINT", "")
        if not endpoint:
            raise ModuleError(
                "generative-aws needs an endpoint (moduleConfig.endpoint "
                "or AWS_BEDROCK_ENDPOINT)")
        model = cfg.get("model", "amazon.titan-text-express-v1")
        out = _post_json(f"{endpoint.rstrip('/')}/model/{model}/invoke",
                         {"inputText": prompt})
        return out.get("outputText") or out.get("results", [{}])[0].get(
            "outputText", "")


# ---- reranker --------------------------------------------------------------


class VoyageAIReranker(Reranker):
    name = "reranker-voyageai"

    def init(self, settings: dict | None = None) -> None:
        self.settings = settings or {}

    def rerank(self, query: str, documents: list[str],
               config: dict) -> list[float]:
        cfg = {**self.settings, **config}
        key = _api_key(cfg, "VOYAGEAI_APIKEY")
        base = (cfg.get("baseURL") or "https://api.voyageai.com/v1"
                ).rstrip("/")
        out = _post_json(f"{base}/rerank",
                         {"query": query, "documents": documents,
                          "model": cfg.get("model", "rerank-lite-1")},
                         headers={"Authorization": f"Bearer {key}"})
        scores = [0.0] * len(documents)
        for item in out.get("data", out.get("results", [])):
            scores[item["index"]] = item["relevance_score"]
        return scores


# ---- readers: qna / ner / sum / spellcheck ---------------------------------


class QnATransformers(QnA):
    """qna-transformers sidecar: POST {origin}/answers/
    {"text", "question"} -> {"answer", "certainty"}."""

    name = "qna-transformers"

    def init(self, settings: dict | None = None) -> None:
        self.base = _origin(settings or {}, "inferenceUrl",
                            "QNA_INFERENCE_API", "http://localhost:8000")

    def answer(self, text: str, question: str, config: dict) -> dict:
        out = _post_json(f"{self.base}/answers",
                         {"text": text, "question": question})
        ans = out.get("answer")
        start = text.find(ans) if ans else -1
        return {"answer": ans, "certainty": out.get("certainty"),
                "hasAnswer": bool(ans),
                "startPosition": max(start, 0),
                "endPosition": start + len(ans) if ans and start >= 0 else 0}


class QnAOpenAI(QnA):
    """qna-openai: answer extraction through a completion prompt
    (modules/qna-openai/clients — 'Please answer the question ...')."""

    name = "qna-openai"

    def init(self, settings: dict | None = None) -> None:
        self.settings = settings or {}

    def answer(self, text: str, question: str, config: dict) -> dict:
        cfg = {**self.settings, **config}
        key = _api_key(cfg, "OPENAI_APIKEY")
        base = (cfg.get("baseURL") or "https://api.openai.com/v1").rstrip("/")
        prompt = (
            "Please answer the question according to the text below. If "
            "the answer is not in the text say 'No answer'.\n\n"
            f"Text: {text}\n\nQuestion: {question}")
        out = _post_json(
            f"{base}/chat/completions",
            {"model": cfg.get("model", "gpt-3.5-turbo"),
             "messages": [{"role": "user", "content": prompt}]},
            headers={"Authorization": f"Bearer {key}"})
        ans = out["choices"][0]["message"]["content"].strip()
        has = ans.lower() not in ("no answer", "no answer.")
        start = text.find(ans) if has else -1
        return {"answer": ans if has else None, "certainty": None,
                "hasAnswer": has, "startPosition": max(start, 0),
                "endPosition": start + len(ans) if has and start >= 0 else 0}


class NERTransformers(NER):
    """ner-transformers sidecar: POST {origin}/ner/ {"text"} ->
    {"tokens": [...]}"""

    name = "ner-transformers"

    def init(self, settings: dict | None = None) -> None:
        self.base = _origin(settings or {}, "inferenceUrl",
                            "NER_INFERENCE_API", "http://localhost:8000")

    def recognize(self, text: str, config: dict) -> list[dict]:
        out = _post_json(f"{self.base}/ner", {"text": text})
        return [{
            "entity": t.get("entity"),
            "word": t.get("word"),
            "certainty": t.get("certainty", t.get("score")),
            "startPosition": t.get("startPosition", t.get("start", 0)),
            "endPosition": t.get("endPosition", t.get("end", 0)),
        } for t in out.get("tokens", [])]


class SumTransformers(Summarizer):
    """sum-transformers sidecar: POST {origin}/sum/ {"text"} ->
    {"summary": ...}"""

    name = "sum-transformers"

    def init(self, settings: dict | None = None) -> None:
        self.base = _origin(settings or {}, "inferenceUrl",
                            "SUM_INFERENCE_API", "http://localhost:8000")

    def summarize(self, text: str, config: dict) -> list[dict]:
        out = _post_json(f"{self.base}/sum", {"text": text})
        summary = out.get("summary")
        if isinstance(summary, list):
            return [{"property": s.get("property", ""),
                     "result": s.get("result", s.get("summary", ""))}
                    for s in summary]
        return [{"property": "", "result": summary or ""}]


class TextSpellCheck(SpellCheck):
    """text-spellcheck sidecar: POST {origin}/spellcheck/ {"text"} ->
    {"text", "changes": [...]}"""

    name = "text-spellcheck"

    def init(self, settings: dict | None = None) -> None:
        self.base = _origin(settings or {}, "inferenceUrl",
                            "SPELLCHECK_INFERENCE_API",
                            "http://localhost:8000")

    def check(self, text: str, config: dict) -> dict:
        out = _post_json(f"{self.base}/spellcheck", {"text": text})
        corrected = out.get("text", text)
        changes = out.get("changes", [])
        return {"originalText": text, "correctedText": corrected,
                "didYouMean": corrected if corrected != text else None,
                "numberOfCorrections": len(changes)}
