"""Recovery observability: what every bucket open found and repaired.

A crash-recovery story is only trustworthy if recovery is VISIBLE: a
bucket that silently truncated a torn WAL tail looks identical to one
that opened clean, and a quarantined segment is data loss an operator
must hear about. Every ``Bucket.__init__`` files a
:class:`BucketRecovery` here; the registry feeds three surfaces:

- a log line at open (WARNING when anything was repaired/quarantined,
  DEBUG when clean),
- the ``weaviate_tpu_recovery_*`` counters (incremented by each open's
  findings, labeled by bucket),
- ``GET /v1/debug/storage`` (api/rest.py), which reports the registry
  snapshot plus rollup totals — the crashtest harness asserts its
  post-restart report is non-empty here.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import asdict, dataclass, field

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_reports: dict[str, "BucketRecovery"] = {}


@dataclass
class BucketRecovery:
    """One bucket open's recovery findings (all zero = opened clean)."""

    bucket: str                      # collection/shard/bucket label
    wal_files_replayed: int = 0      # WAL files found at open
    frames_replayed: int = 0         # intact frames re-applied
    bytes_truncated: int = 0         # torn-tail bytes dropped
    wals_quarantined: int = 0        # WALs renamed .corrupt (mid-file damage)
    segments_quarantined: int = 0    # segments renamed .corrupt at open
    segments_recovered: int = 0      # segments written from replayed WALs
    quarantined_files: list[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return (self.frames_replayed == 0 and self.bytes_truncated == 0
                and self.wals_quarantined == 0
                and self.segments_quarantined == 0)

    def to_dict(self) -> dict:
        d = asdict(self)
        d["clean"] = self.clean
        return d


def record(report: BucketRecovery) -> None:
    """File one bucket open's findings: registry + counters + log."""
    with _lock:
        _reports[report.bucket] = report
    try:
        from weaviate_tpu.runtime import metrics as _m

        if report.frames_replayed:
            _m.recovery_frames_replayed.labels(report.bucket).inc(
                report.frames_replayed)
        if report.bytes_truncated:
            _m.recovery_bytes_truncated.labels(report.bucket).inc(
                report.bytes_truncated)
        if report.wals_quarantined:
            _m.recovery_wals_quarantined.labels(report.bucket).inc(
                report.wals_quarantined)
        if report.segments_quarantined:
            _m.recovery_segments_quarantined.labels(report.bucket).inc(
                report.segments_quarantined)
        if report.segments_recovered:
            _m.recovery_segments_recovered.labels(report.bucket).inc(
                report.segments_recovered)
    except Exception:  # pragma: no cover — registry unavailable
        pass
    if report.clean:
        logger.debug("bucket %s: opened clean", report.bucket)
    else:
        logger.warning(
            "bucket %s: recovery at open — %d frames replayed from %d "
            "WALs (%d segments written), %d torn-tail bytes truncated, "
            "%d WALs + %d segments quarantined%s",
            report.bucket, report.frames_replayed,
            report.wal_files_replayed, report.segments_recovered,
            report.bytes_truncated, report.wals_quarantined,
            report.segments_quarantined,
            f" ({', '.join(report.quarantined_files)})"
            if report.quarantined_files else "")


def snapshot() -> dict:
    """The /v1/debug/storage payload: per-bucket reports + totals."""
    with _lock:
        reports = [r.to_dict() for r in _reports.values()]
    reports.sort(key=lambda r: r["bucket"])
    totals = {
        k: sum(r[k] for r in reports)
        for k in ("wal_files_replayed", "frames_replayed",
                  "bytes_truncated", "wals_quarantined",
                  "segments_quarantined", "segments_recovered")
    }
    totals["buckets"] = len(reports)
    totals["buckets_recovered"] = sum(1 for r in reports if not r["clean"])
    return {"totals": totals, "buckets": reports}


def reset() -> None:
    """Test isolation: forget every filed report."""
    with _lock:
        _reports.clear()
