"""Binary object codec.

Reference: entities/storobj/storage_object.go:567 (MarshalBinary) — a
versioned binary layout of [version, docID, timestamps, UUID, vector(s),
properties]. Here the layout is:

    u8  version (=1)
    u64 doc_id
    u64 creation_time_unix_ms
    u64 last_update_time_unix_ms
    16B uuid (raw bytes)
    u32 n_named_vectors
      per named vector: u16 name_len, name utf8, u32 dim, dim*f32
    u32 props_len, msgpack(properties)

msgpack replaces the reference's JSON property payload (smaller, faster,
schema-free); vectors are raw little-endian f32 exactly like the reference.
"""

from __future__ import annotations

import struct
import time
import uuid as uuid_mod
from dataclasses import dataclass, field

import msgpack
import numpy as np

_VERSION = 1
_HEADER = struct.Struct("<BQQQ16s")


@dataclass
class StorageObject:
    uuid: str
    doc_id: int = 0
    properties: dict = field(default_factory=dict)
    vectors: dict[str, np.ndarray] = field(default_factory=dict)
    creation_time_ms: int = 0
    last_update_time_ms: int = 0

    def __post_init__(self):
        if not self.creation_time_ms:
            self.creation_time_ms = int(time.time() * 1000)
        if not self.last_update_time_ms:
            self.last_update_time_ms = self.creation_time_ms

    @property
    def vector(self) -> np.ndarray | None:
        """Default (unnamed) vector, stored under ''."""
        return self.vectors.get("")

    @vector.setter
    def vector(self, v):
        self.vectors[""] = np.asarray(v, dtype=np.float32)

    def to_bytes(self) -> bytes:
        u = self.uuid
        try:
            # canonical 36-char form: hex-parse directly (uuid.UUID() costs
            # ~5x as much and this runs once per imported object)
            uid = bytes.fromhex(u.replace("-", "")) if len(u) in (32, 36) \
                else uuid_mod.UUID(u).bytes
            if len(uid) != 16:
                uid = uuid_mod.UUID(u).bytes
        except ValueError:
            uid = uuid_mod.UUID(u).bytes
        parts = [
            _HEADER.pack(
                _VERSION,
                self.doc_id,
                self.creation_time_ms,
                self.last_update_time_ms,
                uid,
            ),
            struct.pack("<I", len(self.vectors)),
        ]
        for name, vec in sorted(self.vectors.items()):
            nb = name.encode("utf-8")
            vec = np.ascontiguousarray(vec, dtype=np.float32)
            parts.append(struct.pack("<H", len(nb)))
            parts.append(nb)
            parts.append(struct.pack("<I", vec.shape[0]))
            parts.append(vec.tobytes())
        props = msgpack.packb(self.properties, use_bin_type=True)
        parts.append(struct.pack("<I", len(props)))
        parts.append(props)
        return b"".join(parts)

    @classmethod
    def from_bytes(cls, data: bytes) -> "StorageObject":
        version, doc_id, ctime, mtime, uid = _HEADER.unpack_from(data, 0)
        if version != _VERSION:
            raise ValueError(f"unsupported storage object version {version}")
        off = _HEADER.size
        (n_vecs,) = struct.unpack_from("<I", data, off)
        off += 4
        vectors: dict[str, np.ndarray] = {}
        for _ in range(n_vecs):
            (nlen,) = struct.unpack_from("<H", data, off)
            off += 2
            name = data[off : off + nlen].decode("utf-8")
            off += nlen
            (dim,) = struct.unpack_from("<I", data, off)
            off += 4
            vec = np.frombuffer(data, dtype="<f4", count=dim, offset=off).copy()
            off += 4 * dim
            vectors[name] = vec
        (plen,) = struct.unpack_from("<I", data, off)
        off += 4
        props = msgpack.unpackb(data[off : off + plen], raw=False)
        return cls(
            uuid=str(uuid_mod.UUID(bytes=uid)),
            doc_id=doc_id,
            properties=props,
            vectors=vectors,
            creation_time_ms=ctime,
            last_update_time_ms=mtime,
        )

    def touch(self):
        self.last_update_time_ms = int(time.time() * 1000)

    def content_hash(self) -> bytes:
        """Replica-comparable digest: EXCLUDES doc_id, which is assigned
        per-replica and legitimately differs (replication digests,
        usecases/replica hashtree leaves)."""
        import hashlib

        h = hashlib.sha1()
        h.update(uuid_mod.UUID(self.uuid).bytes)
        h.update(self.last_update_time_ms.to_bytes(8, "little"))
        for name, vec in sorted(self.vectors.items()):
            h.update(name.encode())
            h.update(np.ascontiguousarray(vec, dtype=np.float32).tobytes())
        h.update(msgpack.packb(self.properties, use_bin_type=True))
        return h.digest()[:16]
