"""Host-side persistence: object codec, WAL, LSM-style KV store.

Maps the reference's storage engine (adapters/repos/db/lsmkv — memtable +
WAL + mmap'd sorted segments with bloom filters and strategy-specific
compaction) and the binary object codec (entities/storobj). The TPU engine
holds the hot vector copy in HBM; this layer is the durable source of truth
that rebuilds device state on restart (reference contract: hnsw commit log
replay, startup.go:57).
"""

from weaviate_tpu.storage.objects import StorageObject
from weaviate_tpu.storage.wal import WriteAheadLog
from weaviate_tpu.storage.kv import KVStore, Bucket

__all__ = ["StorageObject", "WriteAheadLog", "KVStore", "Bucket"]
