"""LSM-style KV store with strategy-typed buckets.

Reference: adapters/repos/db/lsmkv — a ``Store`` is a directory of named
``Bucket``s (store.go:36, bucket.go:45), each with an active memtable, a
WAL, and a stack of immutable sorted segments, compacted in the background.
Four value strategies (strategies.go:21-25):

- ``replace``     last write wins (object storage)
- ``set``         unordered value collection with per-value deletes
- ``map``         key -> {mapKey: mapValue} with per-mapKey deletes
- ``roaringset``  key -> bitmap of doc ids (additions/removals sets)

Segment files are mmap'd with an on-disk binary-searchable key index and a
per-segment bloom filter (reference: segment.go:28 mmap, segmentindex/,
segment_bloom_filters.go) — a get-miss costs k bloom probes per segment,
not a footer scan, and opening a segment reads only its footer, O(1) RAM.

The write path never writes segments: a full memtable is *sealed* (memtable
+ its WAL move to a pending list, a fresh WAL starts) and background
maintenance turns sealed memtables into segments (reference: flush cycle in
store_cyclecallbacks.go keeps flushes off the user write path). Batched
writes share one WAL frame and one lock acquisition (``put_many`` /
``map_set_many`` / ``bitmap_add_many``).

doc-id bitmaps are sorted numpy uint64 arrays varint-delta-coded on disk,
the dense analog of the reference's roaring bitmaps (sroar).
"""

from __future__ import annotations

import hashlib
import heapq
import io
import logging
import mmap
import os
import struct
import threading
from typing import Iterable, Iterator

import msgpack
import numpy as np

from weaviate_tpu import native
from weaviate_tpu.runtime import faultline, tracing
from weaviate_tpu.storage import fsutil, recovery
from weaviate_tpu.storage.wal import ReplayReport, WriteAheadLog

logger = logging.getLogger(__name__)

STRATEGIES = ("replace", "set", "map", "roaringset")
_TOMBSTONE = "__tomb__"
_MAGIC_V2 = b"WVS2"
_BLOOM_K = 6
_BLOOM_BITS_PER_KEY = 10


def _merge_values(strategy: str, older, newer):
    """Merge two strategy values, newer taking precedence."""
    if strategy == "replace":
        return newer
    if strategy == "set":
        # value: {"add": set, "del": set}
        add = (older["add"] - newer["del"]) | newer["add"]
        dele = (older["del"] | newer["del"]) - newer["add"]
        return {"add": add, "del": dele}
    if strategy == "map":
        # value: {"set": {k: v}, "del": set} (lazy column form coalesced)
        older = _coalesce_map(older)
        newer = _coalesce_map(newer)
        out = dict(older.get("set", {}))
        for k in newer.get("del", set()):
            out.pop(k, None)
        out.update(newer.get("set", {}))
        dele = (older.get("del", set()) | newer.get("del", set())) - set(
            newer.get("set", {})
        )
        return {"set": out, "del": dele}
    # roaringset: value {"add": np.uint64[], "del": np.uint64[]} — arrays are
    # kept sorted+unique at every boundary so the native C++ set algebra
    # (weaviate_tpu/native, csrc/weaviate_native.cpp) applies directly.
    # Memtable-internal values may be LAZY ({"lazy": [parts...]}) — adds
    # accumulated without merging; coalesce before any algebra.
    older = _coalesce_roaring(older)
    newer = _coalesce_roaring(newer)
    if len(newer["del"]) == 0 and len(older["del"]) == 0:
        # import fast path (adds only): 1 call instead of 4 — the per-key
        # FFI overhead dominated batch imports
        return {"add": native.union_sorted(older["add"], newer["add"]),
                "del": older["del"]}
    add = native.union_sorted(
        native.difference_sorted(older["add"], newer["del"]), newer["add"]
    )
    dele = native.difference_sorted(
        native.union_sorted(older["del"], newer["del"]), newer["add"]
    )
    return {"add": add, "del": dele}


def _coalesce_map(v):
    """Collapse a lazy postings map value ({"plazy": [(docs, tfs, lens),
    ...]}) into canonical {"set": {doc: [tf, len]}, "del": set()} form.
    The import path hands the analyzer's COLUMN arrays straight through;
    the doc->payload dict materializes once per key at read/flush instead
    of once per (term, doc) posting in Python."""
    if isinstance(v, dict) and "plazy" in v:
        out: dict = {}
        for docs, tfs, lens in v["plazy"]:
            for d, t, ln in zip(docs.tolist(), tfs.tolist(), lens.tolist()):
                out[d] = [t, ln]
        return {"set": out, "del": v.get("del", set())}
    return v


def _coalesce_roaring(v):
    """Collapse a lazy memtable roaringset value into canonical
    {"add": sorted-unique u64, "del": ...} form. The memtable appends
    per-write add-arrays to a ``lazy`` list instead of merging each one
    through the set algebra — one np.unique over the concatenation at
    read/flush time replaces hundreds of per-key FFI unions on the
    import hot path."""
    if isinstance(v, dict) and "lazy" in v:
        parts = v["lazy"]
        add = (np.unique(np.concatenate(parts)) if len(parts) > 1
               else parts[0])
        return {"add": add, "del": v["del"]}
    return v


def _sorted_unique_u64(ids) -> np.ndarray:
    """Ascending unique uint64 from any iterable; already-sorted ndarray
    input (the batch analyzer's per-term doc arrays) skips the sort."""
    if isinstance(ids, np.ndarray):
        a = ids.astype(np.uint64, copy=False)
        if len(a) < 2 or bool(np.all(a[1:] > a[:-1])):
            return a
        return np.unique(a)
    return np.unique(np.asarray(list(ids), np.uint64))


def _empty_value(strategy: str):
    if strategy == "replace":
        return None
    if strategy == "set":
        return {"add": set(), "del": set()}
    if strategy == "map":
        return {"set": {}, "del": set()}
    return {"add": np.empty(0, np.uint64), "del": np.empty(0, np.uint64)}


def _pack_value(strategy: str, value) -> bytes:
    if strategy == "replace":
        return msgpack.packb({"v": value}, use_bin_type=True)
    if strategy == "set":
        return msgpack.packb(
            {"add": sorted(value["add"]), "del": sorted(value["del"])},
            use_bin_type=True,
        )
    if strategy == "map":
        return msgpack.packb(
            {"set": value["set"], "del": sorted(value["del"])}, use_bin_type=True
        )
    # roaringset: varint-delta-coded sorted ids (native codec) — ~1 byte/id
    # for dense doc-id runs vs 8 raw (reference: sroar container packing)
    return msgpack.packb(
        {
            "vadd": native.varint_encode(value["add"]),
            "nadd": len(value["add"]),
            "vdel": native.varint_encode(value["del"]),
            "ndel": len(value["del"]),
        },
        use_bin_type=True,
    )


def _unpack_value(strategy: str, raw: bytes):
    obj = msgpack.unpackb(raw, raw=False, strict_map_key=False)
    if strategy == "replace":
        return obj["v"]
    if strategy == "set":
        return {"add": set(obj["add"]), "del": set(obj["del"])}
    if strategy == "map":
        return {"set": obj["set"], "del": set(obj["del"])}
    if "add" in obj:  # pre-varint on-disk format: sorted but NOT deduped
        return {
            "add": np.unique(np.frombuffer(obj["add"], np.uint64)),
            "del": np.unique(np.frombuffer(obj["del"], np.uint64)),
        }
    return {
        "add": native.varint_decode(obj["vadd"], count_hint=obj["nadd"]),
        "del": native.varint_decode(obj["vdel"], count_hint=obj["ndel"]),
    }


def _is_tomb_record(raw: bytes) -> bool:
    obj = msgpack.unpackb(raw, raw=False, strict_map_key=False)
    return isinstance(obj, dict) and obj.get("__tomb__") is True


def _replace_segment_lookup(segments_newest_first, key: bytes):
    """Replace-strategy point lookup over a segment stack: first hit wins,
    tombstones shadow. The bloom key hash is computed once and probed
    against every segment (one blake2b per lookup, not per segment).
    Shared by Bucket.get and Bucket.get_many so batched and single-key
    reads can never diverge."""
    hashes = _bloom_hashes(key) if segments_newest_first else None
    for seg in segments_newest_first:
        raw = seg.get(key, hashes)
        if raw is not None:
            return None if _is_tomb_record(raw) else \
                _unpack_value("replace", raw)
    return None


def _bloom_hashes(key: bytes) -> tuple[int, int]:
    """Two independent 64-bit hashes (double hashing drives k probes)."""
    d = hashlib.blake2b(key, digest_size=16).digest()
    return (
        int.from_bytes(d[:8], "little"),
        int.from_bytes(d[8:], "little") | 1,  # odd => full cycle mod 2^m
    )


class _Segment:
    """Immutable sorted segment file, mmap'd (format v2).

    Layout (little-endian):

        "WVS2"
        [record bytes...]            each value written at its recorded offset
        [keys blob]                  concatenated key bytes
        [index]                      n entries x (koff u64, klen u32, voff u64, vlen u32)
        [bloom]                      u64 words
        footer msgpack {n, keys_off, idx_off, bloom_off, bloom_words}
        u64 footer_off

    Only the footer is parsed at open; key lookups binary-search the on-disk
    index through the mmap (reference: segmentindex/ on-disk b-tree-ish
    index + segment.go:28 mmap) after a bloom-filter check
    (segment_bloom_filters.go).
    """

    _IDX = np.dtype([("koff", "<u8"), ("klen", "<u4"),
                     ("voff", "<u8"), ("vlen", "<u4")])

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        size = os.path.getsize(path)
        if size < 16:
            raise ValueError("segment shorter than header+footer")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        if self._mm[:4] != _MAGIC_V2:
            raise ValueError("segment is not WVS2 format")
        (foot_off,) = struct.unpack_from("<Q", self._mm, size - 8)
        if not 4 <= foot_off <= size - 8:
            raise ValueError("segment footer offset out of range")
        footer = msgpack.unpackb(self._mm[foot_off : size - 8], raw=False)
        try:
            self.n = int(footer["n"])
            keys_off = int(footer["keys_off"])
            idx_off = int(footer["idx_off"])
            bloom_off = int(footer["bloom_off"])
            bloom_words = int(footer["bloom_words"])
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"segment footer malformed: {e}") from e
        if not (4 <= keys_off <= idx_off <= bloom_off <= foot_off):
            raise ValueError("segment footer offsets out of range")
        if idx_off + self.n * self._IDX.itemsize > bloom_off:
            raise ValueError("segment index truncated")
        if bloom_off + bloom_words * 8 > foot_off:
            raise ValueError("segment bloom truncated")
        # zero-copy views into the mmap — O(1) RAM per open segment
        self._idx = np.frombuffer(self._mm, dtype=self._IDX, count=self.n,
                                  offset=idx_off)
        self._bloom = np.frombuffer(self._mm, dtype="<u8", count=bloom_words,
                                    offset=bloom_off)
        self._bloom_bits = bloom_words * 64
        self._keys_off = keys_off
        # validate extremes once so a bit-flipped index can't point outside
        # the file on later reads
        if self.n:
            e0, e1 = self._idx[0], self._idx[self.n - 1]
            for e in (e0, e1):
                if int(e["koff"]) + int(e["klen"]) > idx_off or \
                   int(e["voff"]) + int(e["vlen"]) > keys_off:
                    raise ValueError("segment index offsets out of range")

    # -- key access ----------------------------------------------------------

    def _key_at(self, i: int) -> bytes:
        e = self._idx[i]
        off = int(e["koff"])
        return self._mm[off : off + int(e["klen"])]

    def _value_at(self, i: int) -> bytes:
        e = self._idx[i]
        off = int(e["voff"])
        return self._mm[off : off + int(e["vlen"])]

    def _maybe_contains(self, key: bytes,
                        hashes: tuple[int, int] | None = None) -> bool:
        if self._bloom_bits == 0:
            return self.n > 0
        # the caller may hoist the (relatively costly) key hash and probe
        # many segments with it — one blake2b per lookup, not per segment
        h1, h2 = hashes if hashes is not None else _bloom_hashes(key)
        m = self._bloom_bits
        bloom = self._bloom
        for i in range(_BLOOM_K):
            bit = (h1 + i * h2) % m
            if not (int(bloom[bit >> 6]) >> (bit & 63)) & 1:
                return False
        return True

    def get(self, key: bytes,
            hashes: tuple[int, int] | None = None) -> bytes | None:
        if self.n == 0 or not self._maybe_contains(key, hashes):
            return None
        lo, hi = 0, self.n
        while lo < hi:  # binary search over the on-disk index
            mid = (lo + hi) // 2
            k = self._key_at(mid)
            if k < key:
                lo = mid + 1
            elif k > key:
                hi = mid
            else:
                return self._value_at(mid)
        return None

    def iter_items(self, start: bytes | None = None
                   ) -> Iterator[tuple[bytes, bytes]]:
        lo = 0
        if start is not None:  # binary search the first key >= start
            hi = self.n
            while lo < hi:
                mid = (lo + hi) // 2
                if self._key_at(mid) < start:
                    lo = mid + 1
                else:
                    hi = mid
        for i in range(lo, self.n):
            yield self._key_at(i), self._value_at(i)

    def iter_keys(self) -> Iterator[bytes]:
        for i in range(self.n):
            yield self._key_at(i)

    def close(self) -> None:
        # numpy views pin the mmap buffer — drop them before closing
        self._idx = None
        self._bloom = None
        try:
            self._mm.close()
            self._f.close()
        except (OSError, BufferError):
            pass

    @classmethod
    def write(cls, path: str, items: Iterable[tuple[bytes, bytes]]) -> "_Segment":
        """Write a segment from key-sorted (key, value_bytes) pairs."""
        tmp = path + ".tmp"
        keys: list[bytes] = []
        idx_rows: list[tuple[int, int, int, int]] = []
        with open(tmp, "wb") as f:
            f.write(_MAGIC_V2)
            for k, v in items:
                idx_rows.append((0, len(k), f.tell(), len(v)))
                keys.append(k)
                # crashpoint per record: a crash/torn schedule here
                # leaves a partial segment at .tmp — never renamed, so
                # recovery cannot even see it (the covering WAL replays)
                fsutil.guarded_write(f, v, "segment.write.mid", path=tmp)
            keys_off = f.tell()
            off = keys_off
            for i, k in enumerate(keys):
                koff, klen, voff, vlen = idx_rows[i]
                idx_rows[i] = (off, klen, voff, vlen)
                off += len(k)
                f.write(k)
            idx_off = f.tell()
            idx = np.array(idx_rows, dtype=cls._IDX) if idx_rows else \
                np.empty(0, dtype=cls._IDX)
            f.write(idx.tobytes())
            bloom_off = f.tell()
            n = len(keys)
            bloom_words = max((n * _BLOOM_BITS_PER_KEY + 63) // 64, 1) if n else 0
            bloom = np.zeros(bloom_words, dtype=np.uint64)
            if n:
                m = bloom_words * 64
                for k in keys:
                    h1, h2 = _bloom_hashes(k)
                    for i in range(_BLOOM_K):
                        bit = (h1 + i * h2) % m
                        bloom[bit >> 6] |= np.uint64(1 << (bit & 63))
            f.write(bloom.tobytes())
            foot_off = f.tell()
            f.write(msgpack.packb({
                "n": n, "keys_off": keys_off, "idx_off": idx_off,
                "bloom_off": bloom_off, "bloom_words": bloom_words,
            }, use_bin_type=True))
            f.write(struct.pack("<Q", foot_off))
            f.flush()
            os.fsync(f.fileno())
        # fsync-file -> rename -> fsync-dir: the segment's NAME must be
        # durable before the WAL that covers it may be deleted (fsutil
        # ordering rules; handle already fsynced above)
        fsutil.atomic_replace(tmp, path, fsync_file_first=False,
                              crashpoint="segment.write.pre_rename")
        return cls(path)


class _SegmentV1:
    """Round-1 segment format reader (footer key list in RAM) — kept so
    restores of old backup fileset still open."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            size = f.seek(0, os.SEEK_END)
            if size < 8:
                raise ValueError("segment shorter than its footer pointer")
            f.seek(-8, os.SEEK_END)
            (foot_off,) = struct.unpack("<Q", f.read(8))
            if foot_off > size - 8:
                raise ValueError("segment footer offset out of range")
            f.seek(foot_off)
            footer = msgpack.unpackb(f.read(size - 8 - foot_off), raw=False)
        keys, offs, lens = (footer.get("keys"), footer.get("offs"),
                            footer.get("lens")) if isinstance(footer, dict) \
            else (None, None, None)
        if not (isinstance(keys, list) and isinstance(offs, list)
                and isinstance(lens, list)
                and len(keys) == len(offs) == len(lens)):
            raise ValueError("segment footer malformed")
        for off, ln in zip(offs, lens):
            if not (isinstance(off, int) and isinstance(ln, int)
                    and 0 <= off and 0 <= ln and off + ln <= foot_off):
                raise ValueError("segment footer offsets out of range")
        prev = None
        for k in keys:
            if not isinstance(k, bytes):
                raise ValueError("segment footer key is not bytes")
            if prev is not None and k < prev:
                raise ValueError("segment footer keys out of order")
            prev = k
        self.n = len(keys)
        self.keys: list[bytes] = keys
        self.offs: list[int] = offs
        self.lens: list[int] = lens

    def _maybe_contains(self, key: bytes, hashes=None) -> bool:
        return True

    def get(self, key: bytes, hashes=None) -> bytes | None:
        import bisect

        i = bisect.bisect_left(self.keys, key)
        if i < len(self.keys) and self.keys[i] == key:
            with open(self.path, "rb") as f:
                f.seek(self.offs[i])
                return f.read(self.lens[i])
        return None

    def iter_items(self, start: bytes | None = None
                   ) -> Iterator[tuple[bytes, bytes]]:
        import bisect

        lo = 0 if start is None else bisect.bisect_left(self.keys, start)
        with open(self.path, "rb") as f:
            for i in range(lo, self.n):
                f.seek(self.offs[i])
                yield self.keys[i], f.read(self.lens[i])

    def iter_keys(self) -> Iterator[bytes]:
        yield from self.keys

    def close(self) -> None:
        pass


def _open_segment(path: str):
    with open(path, "rb") as f:
        magic = f.read(4)
    if magic == _MAGIC_V2:
        return _Segment(path)
    return _SegmentV1(path)


class _Memtable:
    """In-RAM sorted-on-demand write buffer backed by one WAL file.

    Two backends: the Python dict (``data``) and, for the two
    inverted-index strategies, the native C++ postings table (``nat``,
    csrc wn_pt_*) — the import hot path runs whole (prop, batch) columns
    through one FFI call there instead of ~15 Python ops per term. The
    dict backend remains the fallback (WEAVIATE_TPU_NO_NATIVE=1) and
    conformance oracle; "map" buckets only opt in via postings_schema
    because the native table fixes the value shape to doc->(tf, len)."""

    __slots__ = ("data", "bytes", "wal", "nat")

    def __init__(self, wal: WriteAheadLog | None, strategy: str | None = None,
                 postings_schema: bool = False):
        self.data: dict[bytes, object] = {}
        self.bytes = 0
        self.wal = wal
        self.nat = None
        if (strategy == "roaringset"
                or (strategy == "map" and postings_schema)):
            if native.available():
                self.nat = native.PostingsTable(strategy)

    @property
    def has_data(self) -> bool:
        if self.nat is not None:
            return len(self.nat) > 0
        return bool(self.data)

    def _nat_apply(self, strategy: str, key: bytes, value) -> None:
        nat = self.nat
        if value is _TOMBSTONE:
            nat.tomb(key)
        elif strategy == "map":
            if "plazy" in value:
                for docs, tfs, lens in value["plazy"]:
                    nat.map_columns([key], np.asarray([0, len(docs)]),
                                    docs, tfs, lens, frame=False)
            else:
                dele = value.get("del") or ()
                if dele:
                    dele = np.asarray(sorted(dele), dtype=np.int64)
                    nat.map_delete([key], np.asarray([0, len(dele)]), dele)
                ent = value.get("set") or {}
                if ent:
                    docs = np.fromiter(ent.keys(), np.int64, len(ent))
                    tfs = np.asarray([v[0] for v in ent.values()], np.uint32)
                    lens = np.asarray([v[1] for v in ent.values()], np.uint32)
                    nat.map_columns([key], np.asarray([0, len(docs)]),
                                    docs, tfs, lens, frame=False)
        else:  # roaringset
            value = _coalesce_roaring(value)
            if len(value["del"]):
                nat.roar([key], np.asarray([0, len(value["del"])]),
                         value["del"], is_del=True, frame=False)
            if len(value["add"]):
                nat.roar([key], np.asarray([0, len(value["add"])]),
                         value["add"], frame=False)
        self.bytes += len(key) + 64

    def apply(self, strategy: str, key: bytes, value) -> None:
        if self.nat is not None:
            self._nat_apply(strategy, key, value)
            return
        cur = self.data.get(key)
        if value is _TOMBSTONE or cur is _TOMBSTONE or cur is None:
            self.data[key] = value
        elif (strategy == "roaringset" and len(value["del"]) == 0
                and (("lazy" in cur) or len(cur["del"]) == 0)):
            # import hot path: APPEND the add-array; coalesce lazily at
            # read/flush (per-key eager unions dominated batch imports)
            if "lazy" in cur:
                cur["lazy"].append(value["add"])
            else:
                self.data[key] = {"lazy": [cur["add"], value["add"]],
                                  "del": cur["del"]}
        elif (strategy == "map" and "plazy" in value
                and ("plazy" in cur or not cur.get("del"))):
            # import hot path: append the analyzer's column arrays; a
            # plain-dict cur (rare mixed writes) absorbs the coalesced
            # columns instead of converting back to arrays
            if "plazy" in cur:
                cur["plazy"].extend(value["plazy"])
            else:
                cur["set"].update(_coalesce_map(value)["set"])
        elif (strategy == "map" and "plazy" not in value
                and not value.get("del") and "plazy" not in cur
                and not cur.get("del")):
            # import hot path: the memtable owns ``cur`` (layer-merged
            # copies are made at read time), so fold the update in place
            # instead of copying both dicts per posting key
            cur["set"].update(value["set"])
        else:
            self.data[key] = _merge_values(strategy, cur, value)
        self.bytes += len(key) + 64

    def packed_items(self, strategy: str) -> Iterator[tuple[bytes, bytes]]:
        if self.nat is not None:
            # one native pass: sorted keys, values already in segment format
            yield from self.nat.packed_items()
            return
        for k in sorted(self.data):
            v = self.data[k]
            if v is _TOMBSTONE:
                yield k, msgpack.packb({"__tomb__": True}, use_bin_type=True)
            else:
                if strategy == "roaringset":
                    v = _coalesce_roaring(v)
                elif strategy == "map":
                    v = _coalesce_map(v)
                yield k, _pack_value(strategy, v)


class Bucket:
    """Named bucket: memtable + WAL + segment stack (reference bucket.go:45).

    Lock discipline: ``_lock`` guards the memtable trio (active, sealed
    list, segment list) and WAL handoff — all O(1) or O(batch) work.
    Segment writes and compaction run outside the lock on immutable
    snapshots; they re-acquire only to swap list entries.
    """

    #: sealed memtables allowed before writers must flush inline
    MAX_SEALED = 4

    def __init__(self, dir_path: str, name: str, strategy: str = "replace",
                 memtable_limit: int = 4 * 1024 * 1024, sync_wal: bool = False,
                 postings_schema: bool = False):
        if strategy not in STRATEGIES:
            raise ValueError(f"unknown strategy {strategy!r}")
        self.name = name
        self.strategy = strategy
        # opt-in native memtable for "map" buckets whose values are
        # postings (doc -> (tf, len)); roaringset buckets always qualify
        self.postings_schema = postings_schema
        self.dir = os.path.join(dir_path, name)
        os.makedirs(self.dir, exist_ok=True)
        self.memtable_limit = memtable_limit
        self.sync_wal = sync_wal
        self._lock = threading.RLock()
        self._flush_lock = threading.Lock()  # serializes segment writers
        self._segments: list = []  # oldest -> newest
        self._sealed: list[_Memtable] = []  # oldest -> newest
        # per-bucket metric children resolved once (reference:
        # lsmkv/metrics.go wires the same vecs per bucket). Label =
        # collection/shard/bucket derived from the directory — a bare
        # bucket name ('searchable') is shared by every shard and would
        # collapse the gauges into last-writer-wins.
        from weaviate_tpu.runtime import metrics as _m

        parts = os.path.normpath(dir_path).split(os.sep)[-2:]
        label = "/".join([p for p in parts if p] + [name])
        self._wal_bytes_metric = _m.lsm_wal_bytes.labels(label)
        self._memtable_metric = _m.lsm_memtable_bytes.labels(label)
        self._flush_metric = _m.lsm_flush_duration.labels(label)
        self._compaction_metric = _m.lsm_compaction_duration.labels(label)
        # recovery report: everything this open repairs/quarantines is
        # filed to storage/recovery (log + counters + /v1/debug/storage)
        self._recovery = recovery.BucketRecovery(label)
        self._load_segments()
        self._wal_seq = 0
        self._write_gen = 0
        self._maintain_gen = -1
        self._mem = self._new_mem(None)
        self._recover_wals()
        if self._mem.wal is None:
            self._mem.wal = self._new_wal()
        recovery.record(self._recovery)

    # -- startup -------------------------------------------------------------

    def _load_segments(self):
        """Open every on-disk segment. Caller holds ``_lock`` — in
        practice __init__, before the bucket is shared."""
        # a crash mid-segment-write leaves a .tmp that was never
        # renamed: invisible to recovery (the covering WAL replays),
        # but clean it up so torn bytes don't accumulate forever
        for f in os.listdir(self.dir):
            if f.endswith(".db.tmp"):
                try:
                    os.remove(os.path.join(self.dir, f))
                except OSError:
                    pass
        segs = sorted(
            f for f in os.listdir(self.dir)
            if f.startswith("segment-") and f.endswith(".db")
        )
        self._segments = []
        for s in segs:
            path = os.path.join(self.dir, s)
            try:
                self._segments.append(_open_segment(path))
            except (ValueError, struct.error, KeyError, TypeError,
                    msgpack.exceptions.UnpackException) as e:
                # parse-shaped failures only: a transient OSError (fd
                # limit, momentary EACCES) must propagate — renaming a
                # HEALTHY segment to .corrupt would silently lose it.
                # A truncated/bit-flipped segment must not brick the whole
                # bucket (reference: corrupt_commit_logs_fixer.go skips
                # unreadable tail entries) — quarantine it and continue;
                # anti-entropy or reimport restores the lost range
                logger.error(
                    "bucket %s: segment %s is corrupt (%s) — quarantined "
                    "as .corrupt, its records are lost", self.name, s, e)
                self._recovery.segments_quarantined += 1
                self._recovery.quarantined_files.append(s)
                try:
                    os.replace(path, path + ".corrupt")
                except OSError:
                    pass
        # monotonic segment sequence — never reuse or go below an existing
        # number, or newest-wins ordering breaks after compaction
        self._next_seq = (
            max((int(s.split("-")[1].split(".")[0]) for s in segs), default=-1) + 1
        )

    def _new_mem(self, wal) -> _Memtable:
        return _Memtable(wal, strategy=self.strategy,
                         postings_schema=self.postings_schema)

    def _new_wal(self) -> WriteAheadLog:
        """Mint the next WAL file. Caller holds ``_lock`` (seal path)
        or runs during single-threaded __init__."""
        path = os.path.join(self.dir, f"wal-{self._wal_seq:06d}.bin")
        self._wal_seq += 1
        return WriteAheadLog(path, sync=self.sync_wal)

    def _recover_wals(self) -> None:
        """Replay every WAL (sealed-but-unflushed + active) into the active
        memtable, oldest first; a single round-1 ``wal.bin`` replays too.
        Caller holds ``_lock`` — __init__, before the bucket is shared."""
        names = sorted(
            f for f in os.listdir(self.dir)
            if (f.startswith("wal-") or f == "wal.bin") and f.endswith(".bin")
        )
        replayed_paths = []
        for nm in names:
            path = os.path.join(self.dir, nm)
            rep = ReplayReport()
            for payload in WriteAheadLog.replay(path, rep):
                rec = msgpack.unpackb(payload, raw=False, strict_map_key=False)
                if "B" in rec:  # raw-value batch frame (map import path)
                    for k, v in rec["B"]:
                        self._mem.apply(
                            self.strategy, k,
                            {"set": v["set"], "del": set(v["del"])})
                elif "P" in rec:  # postings-column map import frame
                    for k, db_, tb, lb in rec["P"]:
                        self._mem.apply(self.strategy, k, {
                            "plazy": [(np.frombuffer(db_, np.int64),
                                       np.frombuffer(tb, np.uint32),
                                       np.frombuffer(lb, np.uint32))],
                            "del": set()})
                elif "R" in rec:  # flat roaringset import frame
                    for k, vadd, nadd, vdel, ndel in rec["R"]:
                        self._mem.apply(self.strategy, k, {
                            "add": native.varint_decode(vadd,
                                                        count_hint=nadd),
                            "del": native.varint_decode(vdel,
                                                        count_hint=ndel)})
                elif "b" in rec:  # batch frame
                    for k, v in rec["b"]:
                        self._mem.apply(
                            self.strategy, k,
                            _unpack_value(self.strategy, v)
                            if v is not None else _TOMBSTONE)
                else:
                    self._mem.apply(
                        self.strategy, rec["k"],
                        _unpack_value(self.strategy, rec["v"])
                        if rec["v"] is not None else _TOMBSTONE)
            replayed_paths.append(path)
            self._recovery.wal_files_replayed += 1
            self._recovery.frames_replayed += rep.frames
            self._recovery.bytes_truncated += rep.bytes_truncated
            if rep.quarantined:
                self._recovery.wals_quarantined += 1
                self._recovery.quarantined_files.append(nm)
            if nm.startswith("wal-"):
                seq = int(nm.split("-")[1].split(".")[0])
                self._wal_seq = max(self._wal_seq, seq + 1)
        if self._mem.has_data:
            # recovered state becomes one (durably renamed) segment;
            # only then may the stale WALs delete — reversing this
            # order would lose the replayed frames to a second crash
            items = list(self._mem.packed_items(self.strategy))
            seg = self._write_segment(items)
            self._segments.append(seg)
            self._mem = self._new_mem(None)
            self._recovery.segments_recovered += 1
        for path in replayed_paths:
            # a quarantined WAL was renamed .corrupt — the remove is a
            # no-op there, the evidence file stays for forensics
            fsutil.remove_durable(path)

    # -- write path ----------------------------------------------------------

    def _log_and_apply(self, key: bytes, value) -> None:
        """Single-record write tail: WAL append, memtable apply, seal
        check. Caller holds ``_lock``."""
        packed = None if value is _TOMBSTONE else _pack_value(self.strategy, value)
        payload = msgpack.packb({"k": key, "v": packed}, use_bin_type=True)
        self._wal_bytes_metric.inc(len(payload))
        self._mem.wal.append(payload)
        self._mem.apply(self.strategy, key, value)
        self._write_gen += 1
        self._memtable_metric.set(self._mem.bytes)
        if self._mem.bytes >= self.memtable_limit:
            self._seal()

    def _append_frame_and_apply(self, payload: bytes, pairs) -> None:
        """Shared tail of every batch write path: WAL append, memtable
        apply, write-gen bump, metrics, seal check. Caller holds _lock."""
        self._wal_bytes_metric.inc(len(payload))
        self._mem.wal.append(payload)
        for k, v in pairs:
            self._mem.apply(self.strategy, k, v)
        self._write_gen += 1
        self._memtable_metric.set(self._mem.bytes)
        if self._mem.bytes >= self.memtable_limit:
            self._seal()

    def _log_and_apply_many(self, pairs: list[tuple[bytes, object]]) -> None:
        """One WAL frame + one memtable pass for a whole batch."""
        if self.strategy == "map" and len(pairs) > 8 and not any(
                v is _TOMBSTONE for _, v in pairs):
            # import hot path: ONE msgpack pack for the whole frame (raw
            # values, "B" tag) instead of one _pack_value per posting key
            frame = [[k, {"set": v["set"], "del": sorted(v["del"])}]
                     for k, v in pairs]
            payload = msgpack.packb({"B": frame}, use_bin_type=True)
            self._append_frame_and_apply(payload, pairs)
            return
        if self.strategy == "roaringset" and len(pairs) > 8 and not any(
                v is _TOMBSTONE for _, v in pairs):
            # import hot path: varint-encode every block in ONE native call
            # and pack ONE flat frame ("R" tag) — a per-key msgpack.packb
            # here was ~10% of the whole import profile
            adds = [v["add"] for _, v in pairs]
            dels = [v["del"] for _, v in pairs]
            enc = native.varint_encode_many(adds + dels)
            n = len(pairs)
            frame = [
                [k, enc[i], len(adds[i]), enc[n + i], len(dels[i])]
                for i, (k, _v) in enumerate(pairs)
            ]
            payload = msgpack.packb({"R": frame}, use_bin_type=True)
            self._append_frame_and_apply(payload, pairs)
            return
        frame = [
            [k, None if v is _TOMBSTONE else _pack_value(self.strategy, v)]
            for k, v in pairs
        ]
        payload = msgpack.packb({"b": frame}, use_bin_type=True)
        self._append_frame_and_apply(payload, pairs)

    def _seal(self) -> None:
        """Active memtable -> sealed list; fresh memtable + WAL. O(1): the
        segment write happens in background maintenance (flush_pending).
        Never flushes inline — the writer applies backpressure AFTER
        releasing ``_lock`` (lock order is _flush_lock -> _lock; flushing
        from under _lock would ABBA-deadlock against maintenance)."""
        if not self._mem.has_data:
            return
        self._sealed.append(self._mem)
        self._mem = self._new_mem(self._new_wal())

    def _backpressure(self) -> None:
        """Writer-side valve, called WITHOUT ``_lock``: when sealed
        memtables back up past MAX_SEALED, the writer pays for one flush
        instead of RAM growing without bound (reference: memtable flush
        blocks the put when the flushing queue backs up).

        Deliberately lock-free HERE, but db-layer callers wrap whole
        batches in shard/collection locks, so the flush's fsync still
        lands inside THEIR critical sections — graftlint G9 baselines
        that cluster; the fix shape (stage under the lock, pay
        backpressure after release) is ROADMAP item 6."""
        if len(self._sealed) > self.MAX_SEALED:
            self.flush_pending(max_tables=1)

    def put(self, key: bytes, value) -> None:
        """replace strategy: store value (any msgpack-able object)."""
        assert self.strategy == "replace"
        with self._lock:
            self._log_and_apply(key, value)
        self._backpressure()

    def put_many(self, pairs: Iterable[tuple[bytes, object]]) -> None:
        assert self.strategy == "replace"
        pairs = list(pairs)
        if not pairs:
            return
        with self._lock:
            self._log_and_apply_many(pairs)
        self._backpressure()

    def delete(self, key: bytes) -> None:
        assert self.strategy == "replace"
        with self._lock:
            self._log_and_apply(key, _TOMBSTONE)
        self._backpressure()

    def delete_many(self, keys: Iterable[bytes]) -> None:
        """Batch tombstones in one WAL frame (import writes one per
        object to clear any prior delete marker — per-key frames were a
        measurable slice of the batch-import profile)."""
        assert self.strategy == "replace"
        keys = list(keys)
        if not keys:
            return
        with self._lock:
            self._log_and_apply_many([(k, _TOMBSTONE) for k in keys])
        self._backpressure()

    def set_add(self, key: bytes, values) -> None:
        assert self.strategy == "set"
        with self._lock:
            self._log_and_apply(key, {"add": set(values), "del": set()})
        self._backpressure()

    def set_remove(self, key: bytes, values) -> None:
        assert self.strategy == "set"
        with self._lock:
            self._log_and_apply(key, {"add": set(), "del": set(values)})
        self._backpressure()

    def map_set(self, key: bytes, mapping: dict) -> None:
        assert self.strategy == "map"
        with self._lock:
            self._log_and_apply(key, {"set": dict(mapping), "del": set()})
        self._backpressure()

    def map_set_many(self, pairs: Iterable[tuple[bytes, dict]]) -> None:
        """Batch of (key, mapping) updates in one WAL frame."""
        assert self.strategy == "map"
        pairs = [(k, {"set": dict(m), "del": set()}) for k, m in pairs]
        if not pairs:
            return
        with self._lock:
            self._log_and_apply_many(pairs)
        self._backpressure()

    def map_set_columns_many(
            self, pairs: list[tuple[bytes, tuple]]) -> None:
        """Import fast path for postings maps: each value is a COLUMN
        triple (docs int64[], tfs, lens) from the batch analyzer. One
        WAL frame of raw array bytes ("P" tag), lazy memtable appends —
        the doc->payload dicts materialize once at read/flush instead of
        per (term, doc) posting in Python."""
        assert self.strategy == "map"
        if not pairs:
            return
        frame = [
            [k, d.astype(np.int64, copy=False).tobytes(),
             np.asarray(t, np.uint32).tobytes(),
             np.asarray(ln, np.uint32).tobytes()]
            for k, (d, t, ln) in pairs
        ]
        payload = msgpack.packb({"P": frame}, use_bin_type=True)
        lazy_pairs = [
            (k, {"plazy": [(np.asarray(d, np.int64),
                            np.asarray(t), np.asarray(ln))],
                 "del": set()})
            for k, (d, t, ln) in pairs
        ]
        with self._lock:
            self._append_frame_and_apply(payload, lazy_pairs)
        self._backpressure()

    def _concat_tail(self, mem, payload: bytes) -> None:
        """Post-native-write tail under _lock: WAL append + accounting
        (the memtable apply already happened inside the native call)."""
        self._wal_bytes_metric.inc(len(payload))
        mem.wal.append(payload)
        mem.bytes = mem.nat.bytes
        self._write_gen += 1
        self._memtable_metric.set(mem.bytes)
        if mem.bytes >= self.memtable_limit:
            self._seal()

    def map_set_columns_concat(self, keys: list[bytes],
                               entry_offs: np.ndarray, docs: np.ndarray,
                               tfs: np.ndarray, lens: np.ndarray,
                               prefix: bytes = b"") -> None:
        """Import fast path: a whole (prop, batch) of postings columns in
        ONE native call — memtable apply and "P" WAL frame come out of
        the same pass (csrc wn_pt_map_columns). Key i is
        prefix + keys[i]; its entries are the [entry_offs[i],
        entry_offs[i+1]) slice of the columns."""
        assert self.strategy == "map"
        if not len(keys):
            return
        if self._mem.nat is None:  # dict-memtable fallback: legacy path
            docs = np.asarray(docs)
            pairs = []
            for i, k in enumerate(keys):
                sl = slice(int(entry_offs[i]), int(entry_offs[i + 1]))
                pairs.append((prefix + k,
                              (docs[sl], np.asarray(tfs)[sl],
                               np.asarray(lens)[sl])))
            return self.map_set_columns_many(pairs)
        with self._lock:
            mem = self._mem
            payload = mem.nat.map_columns(keys, entry_offs, docs, tfs,
                                          lens, prefix=prefix, frame=True)
            self._concat_tail(mem, payload)
        self._backpressure()

    def bitmap_add_concat(self, keys: list[bytes], entry_offs: np.ndarray,
                          ids: np.ndarray, prefix: bytes = b"",
                          is_del: bool = False) -> None:
        """Import fast path twin for roaringset buckets: per-key id blocks
        (unsorted ok) applied + "R"-framed in one native call."""
        assert self.strategy == "roaringset"
        if not len(keys):
            return
        if self._mem.nat is None:
            ids = np.asarray(ids, dtype=np.uint64)
            pairs = [(prefix + k,
                      ids[int(entry_offs[i]):int(entry_offs[i + 1])])
                     for i, k in enumerate(keys)]
            if is_del:
                return self.bitmap_remove_many(pairs)
            return self.bitmap_add_many(pairs)
        with self._lock:
            mem = self._mem
            payload = mem.nat.roar(keys, entry_offs, ids, is_del=is_del,
                                   prefix=prefix, frame=True)
            self._concat_tail(mem, payload)
        self._backpressure()

    def map_delete(self, key: bytes, map_keys) -> None:
        assert self.strategy == "map"
        with self._lock:
            self._log_and_apply(key, {"set": {}, "del": set(map_keys)})
        self._backpressure()

    def map_delete_many(self, pairs: Iterable[tuple[bytes, Iterable]]) -> None:
        assert self.strategy == "map"
        pairs = [(k, {"set": {}, "del": set(mks)}) for k, mks in pairs]
        if not pairs:
            return
        with self._lock:
            self._log_and_apply_many(pairs)
        self._backpressure()

    def bitmap_add(self, key: bytes, ids) -> None:
        assert self.strategy == "roaringset"
        with self._lock:
            self._log_and_apply(
                key,
                {"add": np.unique(np.asarray(list(ids), np.uint64)),
                 "del": np.empty(0, np.uint64)},
            )
        self._backpressure()

    def _bitmap_concat_args(self, pairs):
        """(key, iterable) pairs -> the concat-call triple; shared by the
        add and remove batch paths so their normalization cannot drift."""
        keys = [k for k, _ in pairs]
        blocks = [np.fromiter(v, np.uint64, len(v))
                  if isinstance(v, (set, frozenset))
                  else np.asarray(v).astype(np.uint64, copy=False)
                  for _, v in pairs]
        offs = np.zeros(len(blocks) + 1, dtype=np.int64)
        np.cumsum([len(b) for b in blocks], out=offs[1:])
        ids = (np.concatenate(blocks) if offs[-1]
               else np.empty(0, np.uint64))
        return keys, offs, ids

    def bitmap_add_many(self, pairs: Iterable[tuple[bytes, Iterable]]) -> None:
        assert self.strategy == "roaringset"
        pairs = list(pairs)
        if not pairs:
            return
        if self._mem.nat is not None:
            # route through the one-call native path (it sorts/dedupes
            # each block itself)
            keys, offs, ids = self._bitmap_concat_args(pairs)
            return self.bitmap_add_concat(keys, offs, ids)
        pairs = [
            (k, {"add": _sorted_unique_u64(ids),
                 "del": np.empty(0, np.uint64)})
            for k, ids in pairs
        ]
        with self._lock:
            self._log_and_apply_many(pairs)
        self._backpressure()

    def bitmap_remove(self, key: bytes, ids) -> None:
        assert self.strategy == "roaringset"
        with self._lock:
            self._log_and_apply(
                key,
                {"add": np.empty(0, np.uint64),
                 "del": np.unique(np.asarray(list(ids), np.uint64))},
            )
        self._backpressure()

    def bitmap_remove_many(self, pairs: Iterable[tuple[bytes, Iterable]]) -> None:
        assert self.strategy == "roaringset"
        pairs = list(pairs)
        if not pairs:
            return
        if self._mem.nat is not None:
            keys, offs, ids = self._bitmap_concat_args(pairs)
            return self.bitmap_add_concat(keys, offs, ids, is_del=True)
        pairs = [
            (k, {"add": np.empty(0, np.uint64),
                 "del": np.unique(np.asarray(list(ids), np.uint64))})
            for k, ids in pairs
        ]
        with self._lock:
            self._log_and_apply_many(pairs)
        self._backpressure()

    # -- read path -----------------------------------------------------------

    def get(self, key: bytes):
        """Merged view across memtable + sealed + segments (newest wins).

        ``replace`` walks newest -> oldest and stops at the first hit;
        merge strategies fold oldest -> newest."""
        coalesce = (_coalesce_roaring if self.strategy == "roaringset"
                    else _coalesce_map if self.strategy == "map" else None)
        with self._lock:
            mem_layers = []
            for m in [*self._sealed, self._mem]:
                if m.nat is not None:
                    raw = m.nat.get_packed(key)
                    v = None
                    if raw is not None:
                        v = (_TOMBSTONE if _is_tomb_record(raw)
                             else _unpack_value(self.strategy, raw))
                    mem_layers.append(v)
                    continue
                v = m.data.get(key)
                if coalesce is not None and isinstance(v, dict):
                    canon = coalesce(v)
                    if canon is not v:
                        # write the canonical form back so a hot key is
                        # coalesced once, not on every read
                        m.data[key] = canon
                    v = canon
                mem_layers.append(v)
            segments = list(self._segments)
        if self.strategy == "replace":
            for v in reversed(mem_layers):
                if v is not None:
                    return None if v is _TOMBSTONE else v
            return _replace_segment_lookup(list(reversed(segments)), key)
        layers = []
        for seg in segments:
            raw = seg.get(key)
            if raw is not None:
                layers.append(_TOMBSTONE if _is_tomb_record(raw)
                              else _unpack_value(self.strategy, raw))
        layers.extend(v for v in mem_layers if v is not None)
        if not layers:
            return None
        out = _empty_value(self.strategy)
        seen_any = False
        for layer in layers:
            if layer is _TOMBSTONE:
                out = _empty_value(self.strategy)  # wipes prior layers
                seen_any = False
            else:
                out = _merge_values(self.strategy, out, layer)
                seen_any = True
        return out if seen_any else None

    def get_many(self, keys: list[bytes]) -> list:
        """Batched replace-strategy point lookups: ONE layer snapshot for
        the whole batch instead of a lock + sealed-list copy per key (the
        per-object docid update-check was ~5 us/object of pure snapshot
        overhead on the import path).

        The memtable probes run UNDER the lock, like ``get``'s — the
        active memtable dict keeps mutating under concurrent writers, so
        probing it unlocked could race a resize (and would let the two
        paths diverge). Segments are immutable once listed, so the disk
        lookups for memtable misses happen after the lock drops."""
        assert self.strategy == "replace"
        # faultline point: the batched property-fetch feed (native
        # plane reply building + warm pass read through here) — chaos
        # runs inject errors/latency/corruption without touching disk
        directive = faultline.fire("kv.get_many", bucket=self.name,
                                   n=len(keys))
        misses: list[int] = []
        out: list = []
        with tracing.span("kv.get_many", bucket=self.name, n=len(keys)):
            with self._lock:
                # newest first; replace memtables are always dict-backed
                mems = [m.data for m in [*self._sealed, self._mem][::-1]]
                segments = list(self._segments)[::-1]
                for idx, key in enumerate(keys):
                    for m in mems:
                        v = m.get(key)
                        if v is not None:
                            out.append(None if v is _TOMBSTONE else v)
                            break
                    else:
                        out.append(None)
                        misses.append(idx)
            for idx in misses:
                out[idx] = _replace_segment_lookup(segments, keys[idx])
            if directive == "corrupt":
                # deterministic damage: flip the first byte of every
                # value — consumers must contain the decode failure
                # (error their own reply, never hang or crash the store)
                out = [bytes([v[0] ^ 0xFF]) + v[1:]
                       if isinstance(v, bytes) and v else v for v in out]
            return out

    def get_set(self, key: bytes) -> set:
        v = self.get(key)
        return set() if v is None else set(v["add"])

    def get_map(self, key: bytes) -> dict:
        v = self.get(key)
        return {} if v is None else dict(v["set"])

    def get_bitmap(self, key: bytes) -> np.ndarray:
        v = self.get(key)
        if v is None:
            return np.empty(0, np.uint64)
        return native.difference_sorted(v["add"], v["del"])

    def _merged_layers(self, start: bytes | None = None,
                       stop: bytes | None = None):
        """Snapshot of (segments, memtables oldest->newest) for iteration.

        Sealed memtables are immutable; the ACTIVE memtable keeps mutating
        under concurrent writers, and iteration sorts its keys lazily, so a
        shallow dict copy is taken while still holding the lock (otherwise a
        concurrent put() resizing the dict raises mid-sort). Native-backed
        memtables materialize their [start, stop) items (still packed) in
        one call under the lock."""
        with self._lock:
            mems = []
            for m in [*self._sealed, self._mem]:
                if m.nat is not None:
                    mems.append(m.nat.packed_items(start, stop))
                elif m is self._mem:
                    mems.append(dict(m.data))
                else:
                    mems.append(m.data)
            return list(self._segments), mems

    def iter_merged(self, start: bytes | None = None,
                    stop: bytes | None = None
                    ) -> Iterator[tuple[bytes, object]]:
        """Streaming key-ordered cursor over merged layers, tombstones
        included (value is _TOMBSTONE) — the compaction/scan primitive
        (reference: segment cursors, lsmkv/cursor.go). ``start``/``stop``
        bound the key range [start, stop) — segments seek via their on-disk
        index, so a range scan costs O(log n + range)."""
        segments, mems = self._merged_layers(start, stop)

        def seg_iter(seg, rank):
            for k, raw in seg.iter_items(start=start):
                if stop is not None and k >= stop:
                    return
                v = _TOMBSTONE if _is_tomb_record(raw) else \
                    _unpack_value(self.strategy, raw)
                yield k, rank, v

        def mem_iter(data, rank):
            if isinstance(data, list):  # native table: (key, packed) pairs
                for k, raw in data:
                    v = _TOMBSTONE if _is_tomb_record(raw) else \
                        _unpack_value(self.strategy, raw)
                    yield k, rank, v
                return
            coalesce = (_coalesce_roaring if self.strategy == "roaringset"
                        else _coalesce_map if self.strategy == "map"
                        else None)
            for k in sorted(data):
                if start is not None and k < start:
                    continue
                if stop is not None and k >= stop:
                    return
                v = data[k]
                if coalesce is not None and isinstance(v, dict):
                    v = coalesce(v)
                yield k, rank, v

        iters = [seg_iter(s, i) for i, s in enumerate(segments)]
        iters += [mem_iter(d, len(segments) + i) for i, d in enumerate(mems)]
        merged = heapq.merge(*iters, key=lambda t: (t[0], t[1]))
        cur_key: bytes | None = None
        cur_val = None
        for k, _rank, v in merged:
            if k != cur_key:
                if cur_key is not None:
                    yield cur_key, cur_val
                cur_key, cur_val = k, v
            else:
                if v is _TOMBSTONE or cur_val is _TOMBSTONE:
                    cur_val = v
                else:
                    cur_val = _merge_values(self.strategy, cur_val, v)
        if cur_key is not None:
            yield cur_key, cur_val

    def keys(self) -> list[bytes]:
        return [k for k, v in self.iter_merged() if v is not _TOMBSTONE]

    def iter_items(self) -> Iterator[tuple[bytes, object]]:
        """Cursor over merged live items in key order (reference: segment
        cursors used by the flat index full scan)."""
        for k, v in self.iter_merged():
            if v is not _TOMBSTONE:
                yield k, v

    def iter_range(self, start: bytes | None = None,
                   stop: bytes | None = None
                   ) -> Iterator[tuple[bytes, object]]:
        """Live merged items with keys in [start, stop)."""
        for k, v in self.iter_merged(start, stop):
            if v is not _TOMBSTONE:
                yield k, v

    def __len__(self) -> int:
        n = 0
        for _ in self.iter_items():
            n += 1
        return n

    # -- flush / compaction --------------------------------------------------

    @property
    def dirty(self) -> bool:
        """True when unflushed entries exist (active or sealed memtables)."""
        return self._mem.has_data or bool(self._sealed)

    @property
    def segment_count(self) -> int:
        return len(self._segments)

    def _write_segment(self, items: list[tuple[bytes, bytes]]):
        """Write one segment file. Caller holds ``_flush_lock`` (flush/
        compaction serialization) or runs during single-threaded
        __init__ recovery; ``_next_seq`` is only touched under those."""
        path = os.path.join(self.dir, f"segment-{self._next_seq:06d}.db")
        self._next_seq += 1
        return _Segment.write(path, items)

    def flush_pending(self, max_tables: int | None = None) -> bool:
        """Turn sealed memtables into segments (background work; reference:
        store_cyclecallbacks.go flush cycle). Returns True if flushed any."""
        did = False
        with self._flush_lock:
            while True:
                with self._lock:
                    if not self._sealed:
                        break
                    if max_tables is not None and max_tables <= 0:
                        break
                    mt = self._sealed[0]
                    seq_path = os.path.join(
                        self.dir, f"segment-{self._next_seq:06d}.db")
                    self._next_seq += 1
                    items = list(mt.packed_items(self.strategy))
                # segment write happens outside the bucket lock
                with self._flush_metric.time():
                    seg = _Segment.write(seq_path, items)
                with self._lock:
                    self._segments.append(seg)
                    self._sealed.pop(0)
                if mt.wal is not None:
                    mt.wal.close()
                    # the covering WAL deletes only AFTER the segment's
                    # rename is durable (atomic_replace inside
                    # _Segment.write); a crash in this window replays
                    # the WAL onto the new segment — idempotent
                    fsutil.remove_durable(mt.wal.path,
                                          crashpoint="segment.post_rename")
                did = True
                if max_tables is not None:
                    max_tables -= 1
        return did

    def flush(self) -> None:
        """Force: seal the active memtable and write every pending segment
        (close/backup; reference bucket.FlushMemtable)."""
        with self._lock:
            self._seal()
        self.flush_pending()

    def maintain(self, compact_above: int = 4) -> bool:
        """One background cycle: flush sealed memtables; compact when the
        segment stack grows past the threshold. Seals the active memtable
        only when it is IDLE (no writes since the previous cycle) — a
        steady trickle of small writes must not become one tiny segment
        per cycle plus recurring full-bucket compactions."""
        did = self.flush_pending()
        with self._lock:
            idle = self._write_gen == self._maintain_gen
            self._maintain_gen = self._write_gen
            if self._mem.has_data and not self._sealed and idle:
                self._seal()
        did = self.flush_pending() or did
        if self.segment_count > compact_above:
            self.compact()
            did = True
        return did

    def compact(self) -> None:
        """Merge the current segment stack into one, strategy-aware,
        dropping tombstones (reference: segment_group_compaction.go +
        compactor_{replace,set,map}.go). Streams through a k-way merge —
        peak RAM is O(1) records, not the whole bucket."""
        with self._flush_lock, self._compaction_metric.time():
            with self._lock:
                snapshot = list(self._segments)
            if len(snapshot) <= 1:
                return

            def seg_iter(seg, rank):
                for k, raw in seg.iter_items():
                    v = _TOMBSTONE if _is_tomb_record(raw) else \
                        _unpack_value(self.strategy, raw)
                    yield k, rank, v

            merged = heapq.merge(
                *[seg_iter(s, i) for i, s in enumerate(snapshot)],
                key=lambda t: (t[0], t[1]))

            def live_items():
                cur_key: bytes | None = None
                cur_val = None
                for k, _rank, v in merged:
                    if k != cur_key:
                        if cur_key is not None and cur_val is not _TOMBSTONE:
                            yield cur_key, _pack_value(self.strategy, cur_val)
                        cur_key, cur_val = k, v
                    else:
                        if v is _TOMBSTONE or cur_val is _TOMBSTONE:
                            cur_val = v
                        else:
                            cur_val = _merge_values(self.strategy, cur_val, v)
                if cur_key is not None and cur_val is not _TOMBSTONE:
                    yield cur_key, _pack_value(self.strategy, cur_val)

            # Crash safety: write the merged segment as a NEW higher-seq
            # segment first, then delete the old ones. A crash in between
            # leaves old + merged coexisting, which replays consistently
            # (merge is idempotent; replace takes the newest layer).
            with self._lock:
                path = os.path.join(
                    self.dir, f"segment-{self._next_seq:06d}.db")
                self._next_seq += 1
            # stream the merge straight into the segment writer — peak RAM
            # stays O(1) records even for multi-GB buckets
            merged_seg = _Segment.write(path, live_items())
            if merged_seg.n == 0:
                merged_seg.close()
                try:
                    os.remove(path)
                except OSError:
                    pass
                merged_seg = None
            with self._lock:
                tail = self._segments[len(snapshot):]  # flushed meanwhile
                self._segments = ([merged_seg] if merged_seg else []) + tail
            # unlink only — concurrent readers may still hold the old list
            # snapshot; the inode stays alive until their references drop
            # and GC closes the mmap (POSIX unlink-while-open semantics).
            # Durable unlink: a crash that rolls a delete back leaves
            # old + merged coexisting, which replays consistently, but
            # the fsync keeps the window one crash wide, not unbounded.
            for seg in snapshot:
                fsutil.remove_durable(seg.path)

    def close(self) -> None:
        self.flush()
        with self._lock:
            if self._mem.wal is not None:
                self._mem.wal.close()
                # an empty active WAL leaves no recovery work behind
                try:
                    if os.path.getsize(self._mem.wal.path) == 0:
                        os.remove(self._mem.wal.path)
                except OSError:
                    pass
            for seg in self._segments:
                seg.close()


class KVStore:
    """Directory of named buckets (reference Store, lsmkv/store.go:36)."""

    def __init__(self, dir_path: str, sync_wal: bool = False):
        self.dir = dir_path
        self.sync_wal = sync_wal
        os.makedirs(dir_path, exist_ok=True)
        self._buckets: dict[str, Bucket] = {}
        self._lock = threading.Lock()

    def bucket(self, name: str, strategy: str = "replace", **kwargs) -> Bucket:
        """``sync_wal`` in ``kwargs`` overrides the store default —
        the raft bucket pins ``sync_wal=True`` regardless of config
        (an unsynced vote/log ack breaks raft's safety argument). An
        explicit override that CONTRADICTS an already-open bucket
        raises: silently returning the unsynced instance would make the
        pin a no-op and reopen the double-vote window with zero
        diagnostic."""
        explicit_sync = kwargs.get("sync_wal")
        with self._lock:
            if name not in self._buckets:
                kwargs.setdefault("sync_wal", self.sync_wal)
                self._buckets[name] = Bucket(
                    self.dir, name, strategy, **kwargs
                )
            b = self._buckets[name]
            if b.strategy != strategy:
                raise ValueError(
                    f"bucket {name!r} exists with strategy {b.strategy!r}"
                )
            if explicit_sync is not None and b.sync_wal != explicit_sync:
                raise ValueError(
                    f"bucket {name!r} is already open with sync_wal="
                    f"{b.sync_wal}; an explicit sync_wal={explicit_sync} "
                    "request cannot be honored after the fact")
            return b

    def buckets(self) -> list[Bucket]:
        with self._lock:
            return list(self._buckets.values())

    def close(self) -> None:
        with self._lock:
            for b in self._buckets.values():
                b.close()
            self._buckets.clear()
