"""LSM-style KV store with strategy-typed buckets.

Reference: adapters/repos/db/lsmkv — a ``Store`` is a directory of named
``Bucket``s (store.go:36, bucket.go:45), each with an active memtable, a
WAL, and a stack of immutable sorted segments, compacted in the background.
Four value strategies (strategies.go:21-25):

- ``replace``     last write wins (object storage)
- ``set``         unordered value collection with per-value deletes
- ``map``         key -> {mapKey: mapValue} with per-mapKey deletes
- ``roaringset``  key -> bitmap of doc ids (additions/removals sets)

This implementation keeps the same shapes — memtable + WAL + sorted
segment files + strategy-aware merge/compaction — with a Python core:
segments store a sorted key index in a footer (loaded at open) and values
read on demand, standing in for the reference's mmap'd segments with
bloom filters. doc-id bitmaps are sorted numpy uint64 arrays, the dense
analog of the reference's roaring bitmaps (sroar).
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Iterator

import msgpack
import numpy as np

from weaviate_tpu import native
from weaviate_tpu.storage.wal import WriteAheadLog

STRATEGIES = ("replace", "set", "map", "roaringset")
_TOMBSTONE = "__tomb__"


def _merge_values(strategy: str, older, newer):
    """Merge two strategy values, newer taking precedence."""
    if strategy == "replace":
        return newer
    if strategy == "set":
        # value: {"add": set, "del": set}
        add = (older["add"] - newer["del"]) | newer["add"]
        dele = (older["del"] | newer["del"]) - newer["add"]
        return {"add": add, "del": dele}
    if strategy == "map":
        # value: {"set": {k: v}, "del": set}
        out = dict(older.get("set", {}))
        for k in newer.get("del", set()):
            out.pop(k, None)
        out.update(newer.get("set", {}))
        dele = (older.get("del", set()) | newer.get("del", set())) - set(
            newer.get("set", {})
        )
        return {"set": out, "del": dele}
    # roaringset: value {"add": np.uint64[], "del": np.uint64[]} — arrays are
    # kept sorted+unique at every boundary so the native C++ set algebra
    # (weaviate_tpu/native, csrc/weaviate_native.cpp) applies directly
    add = native.union_sorted(
        native.difference_sorted(older["add"], newer["del"]), newer["add"]
    )
    dele = native.difference_sorted(
        native.union_sorted(older["del"], newer["del"]), newer["add"]
    )
    return {"add": add, "del": dele}


def _empty_value(strategy: str):
    if strategy == "replace":
        return None
    if strategy == "set":
        return {"add": set(), "del": set()}
    if strategy == "map":
        return {"set": {}, "del": set()}
    return {"add": np.empty(0, np.uint64), "del": np.empty(0, np.uint64)}


def _pack_value(strategy: str, value) -> bytes:
    if strategy == "replace":
        return msgpack.packb({"v": value}, use_bin_type=True)
    if strategy == "set":
        return msgpack.packb(
            {"add": sorted(value["add"]), "del": sorted(value["del"])},
            use_bin_type=True,
        )
    if strategy == "map":
        return msgpack.packb(
            {"set": value["set"], "del": sorted(value["del"])}, use_bin_type=True
        )
    # roaringset: varint-delta-coded sorted ids (native codec) — ~1 byte/id
    # for dense doc-id runs vs 8 raw (reference: sroar container packing)
    return msgpack.packb(
        {
            "vadd": native.varint_encode(value["add"]),
            "nadd": len(value["add"]),
            "vdel": native.varint_encode(value["del"]),
            "ndel": len(value["del"]),
        },
        use_bin_type=True,
    )


def _unpack_value(strategy: str, raw: bytes):
    obj = msgpack.unpackb(raw, raw=False, strict_map_key=False)
    if strategy == "replace":
        return obj["v"]
    if strategy == "set":
        return {"add": set(obj["add"]), "del": set(obj["del"])}
    if strategy == "map":
        return {"set": obj["set"], "del": set(obj["del"])}
    if "add" in obj:  # pre-varint on-disk format: sorted but NOT deduped
        return {
            "add": np.unique(np.frombuffer(obj["add"], np.uint64)),
            "del": np.unique(np.frombuffer(obj["del"], np.uint64)),
        }
    return {
        "add": native.varint_decode(obj["vadd"], count_hint=obj["nadd"]),
        "del": native.varint_decode(obj["vdel"], count_hint=obj["ndel"]),
    }


class _Segment:
    """Immutable sorted segment file.

    Layout: [records...][footer msgpack][u64 footer_off]
    footer = {"keys": [...], "offs": [...], "lens": [...]}
    """

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            size = f.seek(0, os.SEEK_END)
            if size < 8:
                raise ValueError("segment shorter than its footer pointer")
            f.seek(-8, os.SEEK_END)
            (foot_off,) = struct.unpack("<Q", f.read(8))
            if foot_off > size - 8:
                raise ValueError("segment footer offset out of range")
            f.seek(foot_off)
            footer = msgpack.unpackb(f.read(size - 8 - foot_off), raw=False)
        keys, offs, lens = (footer.get("keys"), footer.get("offs"),
                            footer.get("lens")) if isinstance(footer, dict) \
            else (None, None, None)
        if not (isinstance(keys, list) and isinstance(offs, list)
                and isinstance(lens, list)
                and len(keys) == len(offs) == len(lens)):
            raise ValueError("segment footer malformed")
        # a bit-flipped footer can parse yet point outside the record
        # region — catch it at open (quarantine) instead of crashing
        # every later read that touches the segment
        for off, ln in zip(offs, lens):
            if not (isinstance(off, int) and isinstance(ln, int)
                    and 0 <= off and 0 <= ln and off + ln <= foot_off):
                raise ValueError("segment footer offsets out of range")
        # keys feed bisect on every read: non-bytes or out-of-order
        # entries would crash or silently miss lookups later
        prev = None
        for k in keys:
            if not isinstance(k, bytes):
                raise ValueError("segment footer key is not bytes")
            if prev is not None and k < prev:
                raise ValueError("segment footer keys out of order")
            prev = k
        self.keys: list[bytes] = keys
        self.offs: list[int] = offs
        self.lens: list[int] = lens

    @classmethod
    def write(cls, path: str, items: list[tuple[bytes, bytes]]) -> "_Segment":
        tmp = path + ".tmp"
        keys, offs, lens = [], [], []
        with open(tmp, "wb") as f:
            for k, v in items:  # items must be key-sorted
                keys.append(k)
                offs.append(f.tell())
                lens.append(len(v))
                f.write(v)
            foot_off = f.tell()
            f.write(msgpack.packb({"keys": keys, "offs": offs, "lens": lens},
                                  use_bin_type=True))
            f.write(struct.pack("<Q", foot_off))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return cls(path)

    def get(self, key: bytes) -> bytes | None:
        import bisect

        i = bisect.bisect_left(self.keys, key)
        if i < len(self.keys) and self.keys[i] == key:
            with open(self.path, "rb") as f:
                f.seek(self.offs[i])
                return f.read(self.lens[i])
        return None

    def iter_items(self) -> Iterator[tuple[bytes, bytes]]:
        with open(self.path, "rb") as f:
            for k, off, ln in zip(self.keys, self.offs, self.lens):
                f.seek(off)
                yield k, f.read(ln)


class Bucket:
    """Named bucket: memtable + WAL + segment stack (reference bucket.go:45)."""

    def __init__(self, dir_path: str, name: str, strategy: str = "replace",
                 memtable_limit: int = 4 * 1024 * 1024, sync_wal: bool = False):
        if strategy not in STRATEGIES:
            raise ValueError(f"unknown strategy {strategy!r}")
        self.name = name
        self.strategy = strategy
        self.dir = os.path.join(dir_path, name)
        os.makedirs(self.dir, exist_ok=True)
        self.memtable_limit = memtable_limit
        self._lock = threading.RLock()
        self._mem: dict[bytes, object] = {}
        self._mem_bytes = 0
        self._segments: list[_Segment] = []  # oldest -> newest
        self._load_segments()
        self._wal = WriteAheadLog(os.path.join(self.dir, "wal.bin"), sync=sync_wal)
        self._replay_wal()

    # -- startup -------------------------------------------------------------

    def _load_segments(self):
        segs = sorted(
            f for f in os.listdir(self.dir)
            if f.startswith("segment-") and f.endswith(".db")
        )
        self._segments = []
        for s in segs:
            path = os.path.join(self.dir, s)
            try:
                self._segments.append(_Segment(path))
            except (ValueError, struct.error, KeyError, TypeError,
                    msgpack.exceptions.UnpackException) as e:
                # parse-shaped failures only: a transient OSError (fd
                # limit, momentary EACCES) must propagate — renaming a
                # HEALTHY segment to .corrupt would silently lose it
                # a truncated/bit-flipped segment must not brick the whole
                # bucket (reference: corrupt_commit_logs_fixer.go skips
                # unreadable tail entries) — quarantine it and continue;
                # anti-entropy or reimport restores the lost range
                import logging

                logging.getLogger(__name__).error(
                    "bucket %s: segment %s is corrupt (%s) — quarantined "
                    "as .corrupt, its records are lost", self.name, s, e)
                try:
                    os.replace(path, path + ".corrupt")
                except OSError:
                    pass
        # monotonic segment sequence — never reuse or go below an existing
        # number, or newest-wins ordering breaks after compaction
        self._next_seq = (
            max((int(s.split("-")[1].split(".")[0]) for s in segs), default=-1) + 1
        )

    def _replay_wal(self):
        for payload in WriteAheadLog.replay(self._wal.path):
            rec = msgpack.unpackb(payload, raw=False, strict_map_key=False)
            self._apply_mem(rec["k"], _unpack_value(self.strategy, rec["v"])
                            if rec["v"] is not None else _TOMBSTONE)

    # -- write path ----------------------------------------------------------

    def _log_and_apply(self, key: bytes, value) -> None:
        packed = None if value is _TOMBSTONE else _pack_value(self.strategy, value)
        self._wal.append(msgpack.packb({"k": key, "v": packed}, use_bin_type=True))
        self._apply_mem(key, value)
        if self._mem_bytes >= self.memtable_limit:
            self.flush()

    def _apply_mem(self, key: bytes, value) -> None:
        cur = self._mem.get(key)
        if value is _TOMBSTONE or cur is _TOMBSTONE or cur is None:
            self._mem[key] = value
        else:
            self._mem[key] = _merge_values(self.strategy, cur, value)
        self._mem_bytes += len(key) + 64

    def put(self, key: bytes, value) -> None:
        """replace strategy: store value (any msgpack-able object)."""
        assert self.strategy == "replace"
        with self._lock:
            self._log_and_apply(key, value)

    def delete(self, key: bytes) -> None:
        assert self.strategy == "replace"
        with self._lock:
            self._log_and_apply(key, _TOMBSTONE)

    def set_add(self, key: bytes, values) -> None:
        assert self.strategy == "set"
        with self._lock:
            self._log_and_apply(key, {"add": set(values), "del": set()})

    def set_remove(self, key: bytes, values) -> None:
        assert self.strategy == "set"
        with self._lock:
            self._log_and_apply(key, {"add": set(), "del": set(values)})

    def map_set(self, key: bytes, mapping: dict) -> None:
        assert self.strategy == "map"
        with self._lock:
            self._log_and_apply(key, {"set": dict(mapping), "del": set()})

    def map_delete(self, key: bytes, map_keys) -> None:
        assert self.strategy == "map"
        with self._lock:
            self._log_and_apply(key, {"set": {}, "del": set(map_keys)})

    def bitmap_add(self, key: bytes, ids) -> None:
        assert self.strategy == "roaringset"
        with self._lock:
            self._log_and_apply(
                key,
                {"add": np.unique(np.asarray(list(ids), np.uint64)),
                 "del": np.empty(0, np.uint64)},
            )

    def bitmap_remove(self, key: bytes, ids) -> None:
        assert self.strategy == "roaringset"
        with self._lock:
            self._log_and_apply(
                key,
                {"add": np.empty(0, np.uint64),
                 "del": np.unique(np.asarray(list(ids), np.uint64))},
            )

    # -- read path -----------------------------------------------------------

    @staticmethod
    def _is_tomb_record(raw: bytes) -> bool:
        obj = msgpack.unpackb(raw, raw=False, strict_map_key=False)
        return isinstance(obj, dict) and obj.get("__tomb__") is True

    def get(self, key: bytes):
        """Merged view across memtable + segments (newest wins)."""
        with self._lock:
            layers = []
            for seg in self._segments:
                raw = seg.get(key)
                if raw is not None:
                    if self._is_tomb_record(raw):
                        layers.append(_TOMBSTONE)
                    else:
                        layers.append(_unpack_value(self.strategy, raw))
            mem = self._mem.get(key)
            if mem is not None:
                layers.append(mem)
            if not layers:
                return None
            if self.strategy == "replace":
                last = layers[-1]
                return None if last is _TOMBSTONE else last
            out = _empty_value(self.strategy)
            seen_any = False
            for layer in layers:
                if layer is _TOMBSTONE:
                    out = _empty_value(self.strategy)  # wipes prior layers
                    seen_any = False
                else:
                    out = _merge_values(self.strategy, out, layer)
                    seen_any = True
            return out if seen_any else None

    def get_set(self, key: bytes) -> set:
        v = self.get(key)
        return set() if v is None else set(v["add"])

    def get_map(self, key: bytes) -> dict:
        v = self.get(key)
        return {} if v is None else dict(v["set"])

    def get_bitmap(self, key: bytes) -> np.ndarray:
        v = self.get(key)
        if v is None:
            return np.empty(0, np.uint64)
        return native.difference_sorted(v["add"], v["del"])

    def keys(self) -> list[bytes]:
        with self._lock:
            out = set()
            for seg in self._segments:
                out.update(seg.keys)
            for k, v in self._mem.items():
                out.add(k)
            live = []
            for k in sorted(out):
                val = self.get(k)
                if self.strategy == "replace":
                    if val is not None:
                        live.append(k)
                else:
                    live.append(k)
            return live

    def iter_items(self) -> Iterator[tuple[bytes, object]]:
        """Cursor over merged live items in key order (reference: segment
        cursors used by the flat index full scan)."""
        for k in self.keys():
            v = self.get(k)
            if v is not None:
                yield k, v

    def __len__(self) -> int:
        return len(self.keys())

    # -- flush / compaction --------------------------------------------------

    @property
    def dirty(self) -> bool:
        """True when the memtable holds unflushed entries."""
        return bool(self._mem)

    @property
    def segment_count(self) -> int:
        return len(self._segments)

    def flush(self) -> None:
        """Memtable -> new segment; WAL truncates (reference: flush cycle,
        store_cyclecallbacks.go)."""
        with self._lock:
            if not self._mem:
                return
            items = []
            for k in sorted(self._mem):
                v = self._mem[k]
                if v is _TOMBSTONE:
                    packed = msgpack.packb({"__tomb__": True}, use_bin_type=True)
                else:
                    packed = _pack_value(self.strategy, v)
                items.append((k, packed))
            path = os.path.join(self.dir, f"segment-{self._next_seq:06d}.db")
            self._next_seq += 1
            self._segments.append(_Segment.write(path, items))
            self._mem.clear()
            self._mem_bytes = 0
            self._wal.reset()

    def compact(self) -> None:
        """Full compaction: merge all segments strategy-aware, drop
        tombstones (reference: segment_group_compaction.go +
        compactor_{replace,set,map}.go)."""
        with self._lock:
            self.flush()
            if len(self._segments) <= 1:
                return
            merged: dict[bytes, object] = {}
            for seg in self._segments:  # oldest -> newest
                for k, raw in seg.iter_items():
                    obj = msgpack.unpackb(raw, raw=False, strict_map_key=False)
                    if isinstance(obj, dict) and obj.get("__tomb__"):
                        merged[k] = _TOMBSTONE
                        continue
                    val = _unpack_value(self.strategy, raw)
                    cur = merged.get(k)
                    if cur is None or cur is _TOMBSTONE:
                        merged[k] = val
                    else:
                        merged[k] = _merge_values(self.strategy, cur, val)
            items = []
            for k in sorted(merged):
                v = merged[k]
                if v is _TOMBSTONE:
                    continue  # tombstones die in full compaction
                items.append((k, _pack_value(self.strategy, v)))
            # Crash safety: write the merged segment as a NEW higher-seq
            # segment first, then delete the old ones. A crash in between
            # leaves old + merged coexisting, which replays consistently
            # (merge is idempotent; replace takes the newest layer).
            old_segments = self._segments
            if items:
                path = os.path.join(self.dir, f"segment-{self._next_seq:06d}.db")
                self._next_seq += 1
                merged_seg = _Segment.write(path, items)
                self._segments = [merged_seg]
            else:
                self._segments = []
            for seg in old_segments:
                os.remove(seg.path)

    def close(self) -> None:
        with self._lock:
            self.flush()
            self._wal.close()


class KVStore:
    """Directory of named buckets (reference Store, lsmkv/store.go:36)."""

    def __init__(self, dir_path: str, sync_wal: bool = False):
        self.dir = dir_path
        self.sync_wal = sync_wal
        os.makedirs(dir_path, exist_ok=True)
        self._buckets: dict[str, Bucket] = {}
        self._lock = threading.Lock()

    def bucket(self, name: str, strategy: str = "replace", **kwargs) -> Bucket:
        with self._lock:
            if name not in self._buckets:
                self._buckets[name] = Bucket(
                    self.dir, name, strategy, sync_wal=self.sync_wal, **kwargs
                )
            b = self._buckets[name]
            if b.strategy != strategy:
                raise ValueError(
                    f"bucket {name!r} exists with strategy {b.strategy!r}"
                )
            return b

    def buckets(self) -> list[Bucket]:
        with self._lock:
            return list(self._buckets.values())

    def close(self) -> None:
        with self._lock:
            for b in self._buckets.values():
                b.close()
            self._buckets.clear()
