"""fsutil: the one durable-write discipline for persistent state.

Every persistence path in this codebase (LSM segments, WAL create/delete
ordering, raft meta/log/snapshot, the HNSW snapshot) funnels its
rename-into-place through :func:`atomic_replace` and its covering-file
deletes through :func:`remove_durable`, so the fsync ordering rules live
in exactly one place (graftlint G7 gates stray ``os.replace`` /
``open(..., "wb")`` in storage/cluster/engine back into here):

1. **fsync the file before the rename.** ``os.replace`` is atomic in
   the namespace but says nothing about the bytes — a crash after the
   rename but before writeback leaves a correctly-named file of
   garbage, which is strictly worse than the old name (recovery can't
   even tell something is missing).
2. **fsync the parent directory after the rename/unlink.** The rename
   itself lives in the directory inode; without the dir fsync a crash
   can roll the NAME back while keeping (or losing) the bytes. The
   classic torn pair this kills: segment rename durable, covering WAL
   delete not — replay then double-applies, which is only safe because
   LSM replay is idempotent; the reverse pair (WAL gone, segment name
   rolled back) loses acked writes and is exactly what rule 2 + delete
   ordering prevent.
3. **Delete covering state only after the covered state is durable.**
   ``remove_durable`` exists so WAL deletes fsync the directory too —
   a deleted-but-not-durably-deleted WAL reappearing after a crash is
   harmless (idempotent replay); the helper keeps the ordering visible.

Crashpoints: the write paths call ``faultline.fire`` at every byte
boundary worth killing a process at; :func:`guarded_write` is the
faultline-armed file wrapper that can tear an in-flight write at byte
granularity (write N bytes of the payload, flush to the kernel, then
``os._exit``) so the crash harness (tools/crashtest) can produce
genuinely-partial frames, not just post-hoc truncations.
"""

from __future__ import annotations

import os

from weaviate_tpu.runtime import faultline


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so renames/unlinks inside it survive a crash.

    No-ops where directories can't be opened for fsync (some
    filesystems / platforms); durability on those is best-effort by
    construction, not silently assumed elsewhere.
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def fsync_file(path: str) -> None:
    """fsync an existing file by path (used when the writer has already
    closed its handle)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_replace(tmp: str, final: str, *, fsync_file_first: bool = True,
                   crashpoint: str | None = None) -> None:
    """Durable rename-into-place: fsync ``tmp`` -> ``os.replace`` ->
    fsync the parent directory.

    ``fsync_file_first=False`` is for callers that already fsynced the
    open handle (segment writer) — the rename + dir-fsync ordering still
    applies. ``crashpoint`` names a faultline point fired between the
    file fsync and the rename (the "bytes durable, name not" window).
    """
    if fsync_file_first:
        fsync_file(tmp)
    if crashpoint is not None:
        faultline.fire(crashpoint, tmp=tmp, final=final)
    os.replace(tmp, final)
    fsync_dir(os.path.dirname(final) or ".")


def remove_durable(path: str, *, crashpoint: str | None = None) -> None:
    """Unlink + parent-dir fsync; missing files are fine (idempotent
    recovery paths re-delete). ``crashpoint`` fires BEFORE the unlink —
    the "covered state durable, covering WAL still present" window the
    crash harness kills in to prove replay is idempotent."""
    if crashpoint is not None:
        faultline.fire(crashpoint, path=path)
    try:
        os.remove(path)
    except OSError:
        return
    fsync_dir(os.path.dirname(path) or ".")


def guarded_write(f, data: bytes, point: str, **attrs) -> None:
    """The faultline-armed file wrapper: write ``data`` to open file
    ``f``, honoring an armed torn-write schedule at ``point``.

    Disarmed this is ``f.write(data)`` plus one module-global read. A
    ``torn`` schedule writes only the first ``torn_bytes`` bytes,
    flushes them to the kernel (they WILL survive the process dying —
    that's the point: a partial frame on disk), then exits with the
    schedule's exit code, simulating process death mid-``write(2)``. A
    ``crash`` schedule exits before writing anything.
    """
    directive = faultline.fire(point, size=len(data), **attrs)
    if isinstance(directive, faultline.Schedule) and \
            directive.action == "torn":
        f.write(data[:max(0, directive.torn_bytes)])
        f.flush()
        os._exit(directive.exit_code)
    f.write(data)
