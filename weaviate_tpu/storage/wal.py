"""Write-ahead log: CRC-framed append-only records with replay.

Reference: adapters/repos/db/lsmkv/commitlogger.go (memtable WAL) and
bucket_recover_from_wal.go (replay on open). Frame layout:

    u32 crc32(payload)   u32 len(payload)   payload

Torn tails (partial final record after a crash) are truncated on replay,
matching the reference's recovery behavior.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Iterator

_FRAME = struct.Struct("<II")


class WriteAheadLog:
    def __init__(self, path: str, sync: bool = False):
        self.path = path
        self.sync = sync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")

    def append(self, payload: bytes) -> None:
        frame = _FRAME.pack(zlib.crc32(payload) & 0xFFFFFFFF, len(payload)) + payload
        with self._lock:
            self._f.write(frame)
            self._f.flush()
            if self.sync:
                os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def size(self) -> int:
        with self._lock:
            return self._f.tell() if not self._f.closed else os.path.getsize(self.path)

    def reset(self) -> None:
        """Truncate after a successful flush (reference: WAL switch on
        memtable flush)."""
        with self._lock:
            self._f.close()
            self._f = open(self.path, "wb")
            self._f.flush()
            if self.sync:
                os.fsync(self._f.fileno())

    @classmethod
    def replay(cls, path: str) -> Iterator[bytes]:
        """Yield intact payloads; stop (and truncate) at the first torn or
        corrupt frame."""
        if not os.path.exists(path):
            return
        good_end = 0
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        while off + _FRAME.size <= len(data):
            crc, ln = _FRAME.unpack_from(data, off)
            start = off + _FRAME.size
            if start + ln > len(data):
                break  # torn tail
            payload = data[start : start + ln]
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                break  # corrupt frame — stop replay here
            yield payload
            off = start + ln
            good_end = off
        if good_end < len(data):
            with open(path, "r+b") as f:
                f.truncate(good_end)
