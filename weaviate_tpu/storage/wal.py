"""Write-ahead log: CRC-framed append-only records with replay.

Reference: adapters/repos/db/lsmkv/commitlogger.go (memtable WAL) and
bucket_recover_from_wal.go (replay on open). Frame layout:

    u32 crc32(payload)   u32 len(payload)   payload

Recovery distinguishes two damage shapes (reference:
corrupt_commit_logs_fixer.go tells tail damage from body damage):

- **torn tail** — the final frame is partial (header or payload cut at
  EOF) or fails its CRC with nothing after it: the classic crash
  mid-append. Truncated to the last good frame, silently correct — the
  writer died before the append was acked.
- **mid-file corruption** — a frame fails its CRC with MORE intact
  bytes after it (bit rot, a torn sector inside the file). Truncating
  would silently discard every later, perfectly good frame, so the file
  is quarantined as ``.corrupt`` instead, the frames before the damage
  are kept, and the bucket keeps replaying its LATER WALs. The
  quarantine is surfaced (recovery report + counters), never silent.

A corrupted length field that points past EOF is indistinguishable from
a torn tail without heuristic resync, so it truncates (the conservative
read of "the file just ends here").

Durability ordering (see storage/fsutil.py for the rules): in sync
mode, a freshly-minted WAL's directory entry is fsynced before any
append is acked, and every append fsyncs before returning — the
``wal.append.pre_fsync`` / ``post_fsync`` / ``wal.create`` crashpoints
let tools/crashtest kill the process at each of those byte boundaries.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import zlib
from dataclasses import dataclass
from typing import Iterator

from weaviate_tpu.runtime import faultline
from weaviate_tpu.storage import fsutil

logger = logging.getLogger(__name__)

_FRAME = struct.Struct("<II")


@dataclass
class ReplayReport:
    """What one WAL replay found — rolled up per bucket into the
    recovery report (storage/recovery.py) and the
    ``weaviate_tpu_recovery_*`` counters."""

    frames: int = 0            # intact frames yielded
    bytes_truncated: int = 0   # torn-tail bytes dropped
    quarantined: bool = False  # file renamed .corrupt (mid-file damage)


class WriteAheadLog:
    def __init__(self, path: str, sync: bool = False):
        self.path = path
        self.sync = sync
        self._lock = threading.Lock()
        parent = os.path.dirname(path) or "."
        os.makedirs(parent, exist_ok=True)
        existed = os.path.exists(path)
        self._f = open(path, "ab")
        if not existed:
            faultline.fire("wal.create", path=path)
            if sync:
                # the file's NAME must be durable before any acked frame
                # references it — else a crash can lose the whole WAL
                # while its appends were acked (fsutil rule 2)
                fsutil.fsync_dir(parent)

    def append(self, payload: bytes) -> None:
        frame = _FRAME.pack(zlib.crc32(payload) & 0xFFFFFFFF,
                            len(payload)) + payload
        with self._lock:
            # crash here = frame absent; torn = partial frame on disk
            fsutil.guarded_write(self._f, frame, "wal.append.pre_fsync",
                                 path=self.path)
            self._f.flush()
            if self.sync:
                os.fsync(self._f.fileno())
            # crash here = frame durable but the ack never returned —
            # the write may legally reappear after restart (idempotent
            # replay), it must never be REQUIRED to
            faultline.fire("wal.append.post_fsync", path=self.path)

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def size(self) -> int:
        with self._lock:
            return self._f.tell() if not self._f.closed else os.path.getsize(self.path)

    def reset(self) -> None:
        """Truncate after a successful flush (reference: WAL switch on
        memtable flush)."""
        with self._lock:
            self._f.close()
            self._f = open(self.path, "wb")
            self._f.flush()
            if self.sync:
                os.fsync(self._f.fileno())

    @classmethod
    def replay(cls, path: str,
               report: ReplayReport | None = None) -> Iterator[bytes]:
        """Yield intact payloads. Torn tails truncate; mid-file
        corruption quarantines the file as ``.corrupt`` (frames before
        the damage are still yielded). ``report``, when given, is
        filled in as replay progresses."""
        report = ReplayReport() if report is None else report
        if not os.path.exists(path):
            return
        good_end = 0
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        while off + _FRAME.size <= len(data):
            crc, ln = _FRAME.unpack_from(data, off)
            start = off + _FRAME.size
            if start + ln > len(data):
                break  # torn tail (payload, or a corrupt length, cut at EOF)
            payload = data[start : start + ln]
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                if start + ln < len(data):
                    # intact bytes FOLLOW the bad frame: not a crash
                    # artifact but body corruption — quarantine so the
                    # later frames (and later WAL files) aren't silently
                    # thrown away with it
                    report.quarantined = True
                    logger.error(
                        "wal %s: frame at offset %d fails CRC with %d "
                        "bytes after it — quarantining as .corrupt "
                        "(%d frames before the damage were replayed)",
                        path, off, len(data) - (start + ln), report.frames)
                    try:
                        os.replace(path, path + ".corrupt")
                    except OSError:
                        pass
                    return
                break  # bad CRC on the final frame — torn write
            yield payload
            report.frames += 1
            off = start + ln
            good_end = off
        if good_end < len(data):
            report.bytes_truncated = len(data) - good_end
            with open(path, "r+b") as f:
                f.truncate(good_end)
