"""ctypes bindings for the native host-runtime library (csrc/).

The reference keeps its runtime in Go with hand-written SIMD only for
distances; our TPU compute path is JAX/Pallas, and the host-side hot loops
— doc-id set algebra, posting-block codecs, cross-shard merge — live in
C++ (csrc/weaviate_native.cpp). Loading strategy:

1. use ``libweaviate_native.so`` next to this file if present,
2. else try to build it with g++ (one-time, ~1s, cached on disk),
3. else fall back to the numpy implementations below (same semantics,
   used on machines without a toolchain and as the conformance oracle).

``available()`` reports which path is active; set ``WEAVIATE_TPU_NO_NATIVE=1``
to force the numpy fallbacks (used by tests to cross-check both paths).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libweaviate_native.so")
_SRC = os.path.join(os.path.dirname(_HERE), os.pardir, "csrc",
                    "weaviate_native.cpp")

_lib = None
_tried = False
_lock = threading.Lock()


def build_and_load(src: str, so: str, link: list[str] | None = None):
    """Compile-if-stale + atomic-replace + dlopen for a native library.
    Shared by this loader and the data-plane loader (dataplane.py).
    Returns the CDLL or None (numpy/Python fallback is safer than a
    stale-ABI .so)."""
    if os.environ.get("WEAVIATE_TPU_NO_NATIVE"):
        return None
    src = os.path.abspath(src)
    stale = (
        os.path.exists(so) and os.path.exists(src)
        and os.path.getmtime(src) > os.path.getmtime(so)
    )
    if not os.path.exists(so) or stale:
        if os.path.exists(src):
            try:
                # build to a per-pid temp path and rename into place:
                # os.replace is atomic, so concurrent processes never
                # dlopen a half-written library
                tmp = f"{so}.{os.getpid()}.tmp"
                subprocess.run(
                    ["g++", "-O3", "-fPIC", "-shared", "-std=c++17",
                     "-o", tmp, src] + (link or []),
                    check=True, capture_output=True, timeout=120,
                    cwd=os.path.dirname(src),
                )
                os.replace(tmp, so)
            except Exception:
                return None
    if not os.path.exists(so):
        return None
    try:
        return ctypes.CDLL(so)
    except OSError:
        return None


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        lib = build_and_load(_SRC, _SO)
        if lib is None:
            return None
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f32p = ctypes.POINTER(ctypes.c_float)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64 = ctypes.c_int64
        i32 = ctypes.c_int32
        for name, args, res in [
            ("wn_intersect_u64", [u64p, i64, u64p, i64, u64p], i64),
            ("wn_union_u64", [u64p, i64, u64p, i64, u64p], i64),
            ("wn_difference_u64", [u64p, i64, u64p, i64, u64p], i64),
            ("wn_membership_i64", [i64p, i64, u64p, i64, u8p], None),
            ("wn_varint_encode_u64", [u64p, i64, u8p], i64),
            ("wn_varint_decode_u64", [u8p, i64, u64p, i64], i64),
            ("wn_merge_topk", [f32p, i64p, i64, i64, i64, f32p, i64p], None),
            ("wn_analyze_batch",
             [u8p, i64p, i64, ctypes.c_int32, i64p, i64p, i64p], i64),
            ("wn_analyze_fetch",
             [u8p, i64p, i64p, i64p, ctypes.POINTER(ctypes.c_uint32), i64p],
             None),
            ("wn_varint_encode_many", [u64p, i64p, i64, u8p, i64p], i64),
            ("wn_storobj_encode_batch",
             [u8p, i64p, u8p, i64p, f32p, i32, i64p, i64p, i64p, i64,
              u8p, i64p], i64),
            ("wn_pt_new", [i32], ctypes.c_void_p),
            ("wn_pt_free", [ctypes.c_void_p], None),
            ("wn_pt_bytes", [ctypes.c_void_p], i64),
            ("wn_pt_count", [ctypes.c_void_p], i64),
            ("wn_pt_map_columns",
             [ctypes.c_void_p, u8p, i64, u8p, i64p, i64, i64p, i64p,
              ctypes.POINTER(ctypes.c_uint32),
              ctypes.POINTER(ctypes.c_uint32), i32], i64),
            ("wn_pt_map_delete",
             [ctypes.c_void_p, u8p, i64, u8p, i64p, i64, i64p, i64p], None),
            ("wn_pt_roar",
             [ctypes.c_void_p, u8p, i64, u8p, i64p, i64, i64p, u64p, i32,
              i32], i64),
            ("wn_pt_tomb", [ctypes.c_void_p, u8p, i64], None),
            ("wn_pt_items", [ctypes.c_void_p, u8p, i64, u8p, i64], i64),
            ("wn_pt_get", [ctypes.c_void_p, u8p, i64], i64),
            ("wn_pt_fetch", [u8p], None),
            ("wn_hnsw_new", [i32, i32], ctypes.c_void_p),
            ("wn_hnsw_free", [ctypes.c_void_p], None),
            ("wn_hnsw_reset", [ctypes.c_void_p, i64], None),
            ("wn_hnsw_set_vectors", [ctypes.c_void_p, i64, i64, f32p], None),
            ("wn_hnsw_set_links", [ctypes.c_void_p, i64, i32, i32, i32p],
             None),
            ("wn_hnsw_set_links_batch",
             [ctypes.c_void_p, i64, i64p, i32p, i32p, i32p], None),
            ("wn_hnsw_clear_links", [ctypes.c_void_p, i64], None),
            ("wn_hnsw_set_tombstones", [ctypes.c_void_p, i64p, i64, i32],
             None),
            ("wn_hnsw_search_layer",
             [ctypes.c_void_p, f32p, i64, i32, i64p, f32p, i64, i64p, f32p],
             i64),
            ("wn_hnsw_search",
             [ctypes.c_void_p, f32p, i64, i64, i64, i32, u8p, i64p, f32p],
             i64),
        ]:
            fn = getattr(lib, name)
            fn.argtypes = args
            fn.restype = res
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _u64(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.uint64))


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


# ---- sorted uint64 set algebra -------------------------------------------


def intersect_sorted(a, b) -> np.ndarray:
    """Intersection of two ascending unique uint64 arrays."""
    a, b = _u64(a), _u64(b)
    lib = _load()
    if lib is None or min(len(a), len(b)) == 0:
        return np.intersect1d(a, b, assume_unique=True)
    out = np.empty(min(len(a), len(b)), dtype=np.uint64)
    n = lib.wn_intersect_u64(_ptr(a, ctypes.c_uint64), len(a),
                             _ptr(b, ctypes.c_uint64), len(b),
                             _ptr(out, ctypes.c_uint64))
    return out[:n]


def union_sorted(a, b) -> np.ndarray:
    a, b = _u64(a), _u64(b)
    lib = _load()
    if lib is None:
        return np.union1d(a, b)
    out = np.empty(len(a) + len(b), dtype=np.uint64)
    n = lib.wn_union_u64(_ptr(a, ctypes.c_uint64), len(a),
                         _ptr(b, ctypes.c_uint64), len(b),
                         _ptr(out, ctypes.c_uint64))
    return out[:n]


def difference_sorted(a, b) -> np.ndarray:
    """a \\ b for ascending unique uint64 arrays."""
    a, b = _u64(a), _u64(b)
    lib = _load()
    if lib is None or len(a) == 0:
        return np.setdiff1d(a, b, assume_unique=True)
    out = np.empty(len(a), dtype=np.uint64)
    n = lib.wn_difference_u64(_ptr(a, ctypes.c_uint64), len(a),
                              _ptr(b, ctypes.c_uint64), len(b),
                              _ptr(out, ctypes.c_uint64))
    return out[:n]


def membership(vals, allow_sorted) -> np.ndarray:
    """Bool mask: vals[i] >= 0 and vals[i] in allow_sorted (ascending u64).

    The doc-id AllowList test of filtered vector search
    (reference: helpers/allow_list.go consumed in flat/index.go:319)."""
    vals = np.ascontiguousarray(np.asarray(vals, dtype=np.int64))
    allow = _u64(allow_sorted)
    lib = _load()
    if lib is None:
        return (vals >= 0) & np.isin(vals, allow.astype(np.int64))
    out = np.empty(len(vals), dtype=np.uint8)
    lib.wn_membership_i64(_ptr(vals, ctypes.c_int64), len(vals),
                          _ptr(allow, ctypes.c_uint64), len(allow),
                          _ptr(out, ctypes.c_uint8))
    return out.astype(bool)


# ---- varint delta codec ---------------------------------------------------


def _varint_encode_py(vals) -> bytes:
    out = bytearray()
    prev = 0
    for v in vals.tolist():
        d = v - prev
        prev = v
        while d >= 0x80:
            out.append((d & 0x7F) | 0x80)
            d >>= 7
        out.append(d)
    return bytes(out)


def varint_encode(vals) -> bytes:
    """Ascending uint64 -> delta + LEB128 bytes (posting-block codec)."""
    vals = _u64(vals)
    if len(vals) <= 16:
        # the ctypes FFI round-trip costs ~15us — for the tiny bitmaps the
        # inverted index writes per unique value, pure Python wins big
        return _varint_encode_py(vals)
    lib = _load()
    if lib is None:
        return _varint_encode_py(vals)
    out = np.empty(len(vals) * 10 or 1, dtype=np.uint8)
    n = lib.wn_varint_encode_u64(_ptr(vals, ctypes.c_uint64), len(vals),
                                 _ptr(out, ctypes.c_uint8))
    return out[:n].tobytes()


def varint_decode(buf: bytes, count_hint: int | None = None) -> np.ndarray:
    """Decode a varint-delta block. ``count_hint`` is the declared element
    count from the surrounding record; a block holding MORE values than
    declared raises (corrupt/truncated data) rather than over- or
    under-reading — the count field is untrusted on-disk input."""
    lib = None if len(buf) <= 32 else _load()  # FFI overhead > tiny decode
    if lib is None:
        out, prev, d, shift = [], 0, 0, 0
        for byte in buf:
            if shift > 63:
                raise ValueError("corrupt varint block: over-long varint")
            d |= (byte & 0x7F) << shift
            if byte & 0x80:
                shift += 7
            else:
                prev += d
                out.append(prev)
                d, shift = 0, 0
        if count_hint is not None and len(out) != count_hint:
            raise ValueError(
                f"corrupt varint block: {len(out)} values, "
                f"{count_hint} declared")
        return np.asarray(out, dtype=np.uint64)
    arr = np.ascontiguousarray(np.frombuffer(buf, dtype=np.uint8))
    # every value takes >= 1 byte, so len(buf) bounds the count — the
    # declared count is untrusted and must never size an allocation alone
    cap = len(buf) if count_hint is None else min(count_hint, len(buf))
    out = np.empty(max(cap, 1), dtype=np.uint64)
    n = lib.wn_varint_decode_u64(_ptr(arr, ctypes.c_uint8), len(arr),
                                 _ptr(out, ctypes.c_uint64), cap)
    if n < 0:
        raise ValueError("corrupt varint block: over-long varint")
    if count_hint is not None and n != count_hint:
        raise ValueError(
            f"corrupt varint block: {n} values, {count_hint} declared")
    return out[:n]


# ---- cross-shard top-k merge ----------------------------------------------


def merge_topk_host(dists: np.ndarray, ids: np.ndarray, k: int):
    """Merge [L, len] ascending per-shard candidates into global top-k.

    ids < 0 mark dead tail slots. Returns (dists [k] f32, ids [k] i64),
    padded with (3e38, -1). The host half of the scatter-gather reduce
    (reference: index.go:1644-1648) when shards answer over the network
    rather than over ICI."""
    dists = np.ascontiguousarray(np.asarray(dists, dtype=np.float32))
    ids = np.ascontiguousarray(np.asarray(ids, dtype=np.int64))
    if dists.ndim == 1:
        dists, ids = dists[None, :], ids[None, :]
    lib = _load()
    if lib is None:
        flat_d, flat_i = dists.ravel(), ids.ravel()
        live = flat_i >= 0
        flat_d, flat_i = flat_d[live], flat_i[live]
        order = np.argsort(flat_d, kind="stable")[:k]
        out_d = np.full(k, 3.0e38, dtype=np.float32)
        out_i = np.full(k, -1, dtype=np.int64)
        out_d[: len(order)] = flat_d[order]
        out_i[: len(order)] = flat_i[order]
        return out_d, out_i
    out_d = np.empty(k, dtype=np.float32)
    out_i = np.empty(k, dtype=np.int64)
    lib.wn_merge_topk(_ptr(dists, ctypes.c_float), _ptr(ids, ctypes.c_int64),
                      dists.shape[0], dists.shape[1], k,
                      _ptr(out_d, ctypes.c_float), _ptr(out_i, ctypes.c_int64))
    return out_d, out_i


# ---- batch storobj frame encoder ------------------------------------------


def storobj_encode_batch(uuid_strs: list[bytes], props_blobs: list[bytes],
                         vectors: np.ndarray, doc_ids: np.ndarray,
                         created_ms: np.ndarray, updated_ms: np.ndarray):
    """Encode N storage-object value frames (single unnamed vector each)
    in one native call; byte-identical to StorageObject.to_bytes.

    ``uuid_strs``: canonical-form uuid strings as bytes; ``props_blobs``:
    caller-msgpacked property dicts; ``vectors``: [n, dim] f32.
    Returns a list of ``bytes`` frames, or None when the native library
    is unavailable or a uuid fails the fast parse (callers fall back to
    the Python encoder).
    """
    lib = _load()
    if lib is None:
        return None
    n, dim = vectors.shape
    uuids = b"".join(uuid_strs)
    uoffs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(u) for u in uuid_strs], out=uoffs[1:])
    props = b"".join(props_blobs)
    poffs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(b) for b in props_blobs], out=poffs[1:])
    # fixed part: 41 header + 4 n_vecs + 2 name_len + 4 dim + 4 props_len
    frame_offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(np.diff(poffs) + (55 + 4 * dim), out=frame_offs[1:])
    out = np.empty(int(frame_offs[-1]), dtype=np.uint8)
    vectors = np.ascontiguousarray(vectors, dtype=np.float32)
    doc_ids = np.ascontiguousarray(doc_ids, dtype=np.int64)
    created_ms = np.ascontiguousarray(created_ms, dtype=np.int64)
    updated_ms = np.ascontiguousarray(updated_ms, dtype=np.int64)
    ub = np.frombuffer(uuids, dtype=np.uint8) if uuids else \
        np.empty(0, np.uint8)
    pb = np.frombuffer(props, dtype=np.uint8) if props else \
        np.empty(0, np.uint8)
    rc = lib.wn_storobj_encode_batch(
        _ptr(ub, ctypes.c_uint8), _ptr(uoffs, ctypes.c_int64),
        _ptr(pb, ctypes.c_uint8), _ptr(poffs, ctypes.c_int64),
        _ptr(vectors, ctypes.c_float), ctypes.c_int32(dim),
        _ptr(doc_ids, ctypes.c_int64), _ptr(created_ms, ctypes.c_int64),
        _ptr(updated_ms, ctypes.c_int64), ctypes.c_int64(n),
        _ptr(out, ctypes.c_uint8), _ptr(frame_offs, ctypes.c_int64))
    if rc != 0:
        return None
    # one copy per frame (ndarray slices are views; .tobytes() on each
    # materializes just that frame — no whole-buffer duplicate)
    return [out[frame_offs[i]:frame_offs[i + 1]].tobytes()
            for i in range(n)]


# ---- batch text analyzer --------------------------------------------------

_MODE_BY_TOKENIZATION = {"word": 0, "lowercase": 1, "whitespace": 2,
                         "field": 3}


def analyze_batch(values: list[str], tokenization: str):
    """Tokenize + accumulate a batch of ASCII text values in ONE native
    call (the import hot loop — reference inverted/analyzer.go per put).

    Returns (terms [list of str, sorted], entry_offs [nterms+1],
    entry_rows [E], entry_tfs [E], row_tokens [nrows]) — for each term,
    entries rows/tfs slice [entry_offs[t]:entry_offs[t+1]] give the value
    indices containing it and their term frequencies (rows ascending).
    Returns None when the native library is unavailable (callers fall
    back to the Python tokenizer).
    """
    lib = _load()
    if lib is None:
        return None
    mode = _MODE_BY_TOKENIZATION[tokenization]
    blob = "".join(values).encode("ascii")
    offs = np.zeros(len(values) + 1, dtype=np.int64)
    np.cumsum([len(v) for v in values], out=offs[1:])
    nterms = ctypes.c_int64()
    nentries = ctypes.c_int64()
    termbytes = ctypes.c_int64()
    blob_arr = np.frombuffer(blob, dtype=np.uint8) if blob else \
        np.zeros(1, dtype=np.uint8)
    lib.wn_analyze_batch(
        _ptr(np.ascontiguousarray(blob_arr), ctypes.c_uint8),
        _ptr(offs, ctypes.c_int64), len(values), mode,
        ctypes.byref(nterms), ctypes.byref(nentries), ctypes.byref(termbytes))
    nt, ne, tb = nterms.value, nentries.value, termbytes.value
    terms_blob = np.empty(max(tb, 1), dtype=np.uint8)
    term_offs = np.empty(nt + 1, dtype=np.int64)
    entry_offs = np.empty(nt + 1, dtype=np.int64)
    entry_rows = np.empty(max(ne, 1), dtype=np.int64)
    entry_tfs = np.empty(max(ne, 1), dtype=np.uint32)
    row_tokens = np.empty(max(len(values), 1), dtype=np.int64)
    lib.wn_analyze_fetch(
        _ptr(terms_blob, ctypes.c_uint8), _ptr(term_offs, ctypes.c_int64),
        _ptr(entry_offs, ctypes.c_int64), _ptr(entry_rows, ctypes.c_int64),
        _ptr(entry_tfs, ctypes.c_uint32), _ptr(row_tokens, ctypes.c_int64))
    raw = terms_blob.tobytes()
    # terms stay BYTES: every consumer (posting keys, cache keys) wants
    # prefix + term as bytes — decoding to str here forced an immediate
    # re-encode per term on the import hot path
    terms = [raw[term_offs[t]:term_offs[t + 1]] for t in range(nt)]
    return (terms, entry_offs, entry_rows[:ne], entry_tfs[:ne],
            row_tokens[:len(values)])


def varint_encode_many(arrays: list[np.ndarray]):
    """Encode many ascending-u64 blocks in one call.

    Returns list of bytes per block (Python fallback when no native lib).
    """
    lib = _load()
    if lib is None or not arrays:
        return [varint_encode(a) for a in arrays]
    concat = np.concatenate([_u64(a) for a in arrays]) if arrays else \
        np.empty(0, np.uint64)
    offs = np.zeros(len(arrays) + 1, dtype=np.int64)
    np.cumsum([len(a) for a in arrays], out=offs[1:])
    out = np.empty(max(int(offs[-1]) * 10, 1), dtype=np.uint8)
    lens = np.empty(len(arrays), dtype=np.int64)
    total = lib.wn_varint_encode_many(
        _ptr(np.ascontiguousarray(concat) if len(concat) else
             np.zeros(1, np.uint64), ctypes.c_uint64),
        _ptr(offs, ctypes.c_int64), len(arrays),
        _ptr(out, ctypes.c_uint8), _ptr(lens, ctypes.c_int64))
    blob = out[:total].tobytes()
    res = []
    pos = 0
    for n in lens.tolist():
        res.append(blob[pos:pos + n])
        pos += n
    return res


# ---- HNSW graph walker (csrc wn_hnsw_*) ----------------------------------

# engine/hnsw.py metric names -> native metric ids (csrc hnsw_dist)
_HNSW_METRIC_IDS = {"l2-squared": 0, "dot": 1, "cosine": 2, "cosine-dot": 2,
                    "manhattan": 3, "hamming": 4}


def hnsw_supported(metric: str) -> bool:
    return available() and metric in _HNSW_METRIC_IDS


class HnswNative:
    """Native mirror of an HNSW graph.

    The graph-search hot loop (reference search.go:173-341) runs in C++
    over a mirrored copy of the Python graph; engine/hnsw.py keeps the
    mirror current incrementally (_set_links / vector writes /
    tombstones) and re-uploads in one batched sync after bulk mutations.
    There is deliberately NO numpy fallback here — when the native lib
    is absent the engine keeps its original Python walker, which IS the
    fallback (and the conformance oracle in tests/test_hnsw.py).
    """

    def __init__(self, dim: int, metric: str):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.dim = int(dim)
        self._h = ctypes.c_void_p(
            lib.wn_hnsw_new(self.dim, _HNSW_METRIC_IDS[metric]))

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.wn_hnsw_free(self._h)
                self._h = None
        except Exception:
            pass

    def reset(self, cap: int):
        self._lib.wn_hnsw_reset(self._h, int(cap))

    def set_vectors(self, slot0: int, vecs: np.ndarray):
        vecs = np.ascontiguousarray(vecs, dtype=np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None, :]
        self._lib.wn_hnsw_set_vectors(self._h, int(slot0), len(vecs),
                                      _ptr(vecs, ctypes.c_float))

    def set_links(self, slot: int, layer: int, neigh: np.ndarray):
        neigh = np.ascontiguousarray(neigh, dtype=np.int32)
        self._lib.wn_hnsw_set_links(self._h, int(slot), int(layer),
                                    len(neigh), _ptr(neigh, ctypes.c_int32))

    def set_links_batch(self, slots: np.ndarray, layers: np.ndarray,
                        counts: np.ndarray, neigh: np.ndarray):
        slots = np.ascontiguousarray(slots, dtype=np.int64)
        layers = np.ascontiguousarray(layers, dtype=np.int32)
        counts = np.ascontiguousarray(counts, dtype=np.int32)
        neigh = np.ascontiguousarray(neigh, dtype=np.int32)
        self._lib.wn_hnsw_set_links_batch(
            self._h, len(slots), _ptr(slots, ctypes.c_int64),
            _ptr(layers, ctypes.c_int32), _ptr(counts, ctypes.c_int32),
            _ptr(neigh, ctypes.c_int32))

    def clear_links(self, slot: int):
        self._lib.wn_hnsw_clear_links(self._h, int(slot))

    def set_tombstones(self, slots, val: bool = True):
        slots = np.ascontiguousarray(slots, dtype=np.int64)
        if len(slots) == 0:
            return
        self._lib.wn_hnsw_set_tombstones(self._h, _ptr(slots, ctypes.c_int64),
                                         len(slots), 1 if val else 0)

    def search_layer(self, q: np.ndarray, ef: int, layer: int,
                     ep_slots: np.ndarray, ep_dists: np.ndarray):
        """One-layer ef-search (insert path). Returns (dists, slots)
        ascending; tombstoned nodes included, as in the Python walker."""
        q = np.ascontiguousarray(q, dtype=np.float32)
        ep_slots = np.ascontiguousarray(ep_slots, dtype=np.int64)
        ep_dists = np.ascontiguousarray(ep_dists, dtype=np.float32)
        cap = int(ef) + len(ep_slots)
        out_s = np.empty(cap, dtype=np.int64)
        out_d = np.empty(cap, dtype=np.float32)
        n = self._lib.wn_hnsw_search_layer(
            self._h, _ptr(q, ctypes.c_float), int(ef), int(layer),
            _ptr(ep_slots, ctypes.c_int64), _ptr(ep_dists, ctypes.c_float),
            len(ep_slots), _ptr(out_s, ctypes.c_int64),
            _ptr(out_d, ctypes.c_float))
        return out_d[:n], out_s[:n]

    def search(self, q: np.ndarray, k: int, ef: int, ep: int,
               max_level: int, allow: np.ndarray | None = None):
        """Fused query search: greedy descent + layer-0 ef-search +
        live/allowed output filter. Returns (dists, slots) ascending."""
        q = np.ascontiguousarray(q, dtype=np.float32)
        out_s = np.empty(max(int(k), 1), dtype=np.int64)
        out_d = np.empty(max(int(k), 1), dtype=np.float32)
        if allow is not None:
            allow = np.ascontiguousarray(allow, dtype=np.uint8)
            ap = _ptr(allow, ctypes.c_uint8)
        else:
            ap = None
        n = self._lib.wn_hnsw_search(
            self._h, _ptr(q, ctypes.c_float), int(k), int(ef), int(ep),
            int(max_level), ap, _ptr(out_s, ctypes.c_int64),
            _ptr(out_d, ctypes.c_float))
        return out_d[:n], out_s[:n]


# ---- postings memtable (csrc wn_pt_*) ------------------------------------


def _keys_blob(keys: list[bytes]):
    blob = b"".join(keys)
    offs = np.zeros(len(keys) + 1, dtype=np.int64)
    np.cumsum([len(k) for k in keys], out=offs[1:])
    return np.frombuffer(blob, dtype=np.uint8) if blob else \
        np.zeros(1, np.uint8), offs


_EMPTY_U8 = None


def _empty_u8():
    global _EMPTY_U8
    if _EMPTY_U8 is None:
        _EMPTY_U8 = np.zeros(1, dtype=np.uint8)
    return _EMPTY_U8


class PostingsTable:
    """Native memtable for the "map" / "roaringset" LSM strategies.

    One instance backs one kv.py _Memtable; the Python dict memtable is
    the fallback (WEAVIATE_TPU_NO_NATIVE=1) and conformance oracle.
    Batched writes return the WAL frame payload produced in the same
    native call; reads come back as msgpack documents in the exact
    shapes kv.py _unpack_value produces.
    """

    def __init__(self, strategy: str):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.strategy = strategy
        self._h = ctypes.c_void_p(
            lib.wn_pt_new(0 if strategy == "map" else 1))

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.wn_pt_free(self._h)
                self._h = None
        except Exception:
            pass

    @property
    def bytes(self) -> int:
        return self._lib.wn_pt_bytes(self._h)

    def __len__(self) -> int:
        return self._lib.wn_pt_count(self._h)

    def _fetch(self, n: int) -> bytes:
        out = np.empty(max(n, 1), dtype=np.uint8)
        self._lib.wn_pt_fetch(_ptr(out, ctypes.c_uint8))
        return out[:n].tobytes()

    def map_columns(self, keys: list[bytes], entry_offs: np.ndarray,
                    docs: np.ndarray, tfs: np.ndarray, lens: np.ndarray,
                    prefix: bytes = b"", frame: bool = True) -> bytes | None:
        """Apply per-key postings columns; returns the "P" WAL frame."""
        kb, koffs = _keys_blob(keys)
        docs = np.ascontiguousarray(docs, dtype=np.int64)
        tfs = np.ascontiguousarray(tfs, dtype=np.uint32)
        lens = np.ascontiguousarray(lens, dtype=np.uint32)
        entry_offs = np.ascontiguousarray(entry_offs, dtype=np.int64)
        pfx = (np.frombuffer(prefix, dtype=np.uint8) if prefix
               else _empty_u8())
        n = self._lib.wn_pt_map_columns(
            self._h, _ptr(pfx, ctypes.c_uint8), len(prefix),
            _ptr(kb, ctypes.c_uint8), _ptr(koffs, ctypes.c_int64),
            len(keys), _ptr(entry_offs, ctypes.c_int64),
            _ptr(docs if len(docs) else np.zeros(1, np.int64),
                 ctypes.c_int64),
            _ptr(tfs if len(tfs) else np.zeros(1, np.uint32),
                 ctypes.c_uint32),
            _ptr(lens if len(lens) else np.zeros(1, np.uint32),
                 ctypes.c_uint32),
            1 if frame else 0)
        return self._fetch(n) if frame else None

    def map_delete(self, keys: list[bytes], entry_offs: np.ndarray,
                   del_docs: np.ndarray):
        kb, koffs = _keys_blob(keys)
        del_docs = np.ascontiguousarray(del_docs, dtype=np.int64)
        entry_offs = np.ascontiguousarray(entry_offs, dtype=np.int64)
        self._lib.wn_pt_map_delete(
            self._h, _ptr(_empty_u8(), ctypes.c_uint8), 0,
            _ptr(kb, ctypes.c_uint8), _ptr(koffs, ctypes.c_int64),
            len(keys), _ptr(entry_offs, ctypes.c_int64),
            _ptr(del_docs if len(del_docs) else np.zeros(1, np.int64),
                 ctypes.c_int64))

    def roar(self, keys: list[bytes], entry_offs: np.ndarray,
             ids: np.ndarray, is_del: bool = False, prefix: bytes = b"",
             frame: bool = True) -> bytes | None:
        """Apply per-key id blocks (unsorted ok); returns the "R" frame."""
        kb, koffs = _keys_blob(keys)
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        entry_offs = np.ascontiguousarray(entry_offs, dtype=np.int64)
        pfx = (np.frombuffer(prefix, dtype=np.uint8) if prefix
               else _empty_u8())
        n = self._lib.wn_pt_roar(
            self._h, _ptr(pfx, ctypes.c_uint8), len(prefix),
            _ptr(kb, ctypes.c_uint8), _ptr(koffs, ctypes.c_int64),
            len(keys), _ptr(entry_offs, ctypes.c_int64),
            _ptr(ids if len(ids) else np.zeros(1, np.uint64),
                 ctypes.c_uint64),
            1 if is_del else 0, 1 if frame else 0)
        return self._fetch(n) if frame else None

    def tomb(self, key: bytes):
        kb = np.frombuffer(key, dtype=np.uint8)
        self._lib.wn_pt_tomb(self._h, _ptr(kb, ctypes.c_uint8), len(key))

    def get_packed(self, key: bytes) -> bytes | None:
        """msgpack value for one key (kv.py _unpack_value shape), or None."""
        kb = np.frombuffer(key, dtype=np.uint8) if key else _empty_u8()
        n = self._lib.wn_pt_get(self._h, _ptr(kb, ctypes.c_uint8), len(key))
        if n < 0:
            return None
        return self._fetch(n)

    def packed_items(self, start: bytes | None = None,
                     stop: bytes | None = None):
        """Ascending (key, msgpack-value) pairs in [start, stop)."""
        sb = (np.frombuffer(start, dtype=np.uint8) if start
              else _empty_u8())
        tb = (np.frombuffer(stop, dtype=np.uint8) if stop
              else _empty_u8())
        n = self._lib.wn_pt_items(
            self._h, _ptr(sb, ctypes.c_uint8),
            len(start) if start is not None else -1,
            _ptr(tb, ctypes.c_uint8),
            len(stop) if stop is not None else -1)
        blob = self._fetch(n)
        out = []
        pos = 0
        while pos < len(blob):
            kl = int.from_bytes(blob[pos:pos + 4], "little")
            pos += 4
            k = blob[pos:pos + kl]
            pos += kl
            vl = int.from_bytes(blob[pos:pos + 4], "little")
            pos += 4
            out.append((k, blob[pos:pos + vl]))
            pos += vl
        return out
