"""ctypes bindings for the native host-runtime library (csrc/).

The reference keeps its runtime in Go with hand-written SIMD only for
distances; our TPU compute path is JAX/Pallas, and the host-side hot loops
— doc-id set algebra, posting-block codecs, cross-shard merge — live in
C++ (csrc/weaviate_native.cpp). Loading strategy:

1. use ``libweaviate_native.so`` next to this file if present,
2. else try to build it with g++ (one-time, ~1s, cached on disk),
3. else fall back to the numpy implementations below (same semantics,
   used on machines without a toolchain and as the conformance oracle).

``available()`` reports which path is active; set ``WEAVIATE_TPU_NO_NATIVE=1``
to force the numpy fallbacks (used by tests to cross-check both paths).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libweaviate_native.so")
_SRC = os.path.join(os.path.dirname(_HERE), os.pardir, "csrc",
                    "weaviate_native.cpp")

_lib = None
_tried = False
_lock = threading.Lock()


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("WEAVIATE_TPU_NO_NATIVE"):
            return None
        src = os.path.abspath(_SRC)
        stale = (
            os.path.exists(_SO) and os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(_SO)
        )
        if not os.path.exists(_SO) or stale:
            if os.path.exists(src):
                try:
                    # build to a per-pid temp path and rename into place:
                    # os.replace is atomic, so concurrent processes never
                    # dlopen a half-written library
                    tmp = f"{_SO}.{os.getpid()}.tmp"
                    subprocess.run(
                        ["g++", "-O3", "-fPIC", "-shared", "-std=c++17",
                         "-o", tmp, src],
                        check=True, capture_output=True, timeout=120,
                    )
                    os.replace(tmp, _SO)
                except Exception:
                    # a stale .so may have the wrong ABI — numpy fallback
                    # is safer than loading it
                    return None
        if not os.path.exists(_SO):
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f32p = ctypes.POINTER(ctypes.c_float)
        i64 = ctypes.c_int64
        for name, args, res in [
            ("wn_intersect_u64", [u64p, i64, u64p, i64, u64p], i64),
            ("wn_union_u64", [u64p, i64, u64p, i64, u64p], i64),
            ("wn_difference_u64", [u64p, i64, u64p, i64, u64p], i64),
            ("wn_membership_i64", [i64p, i64, u64p, i64, u8p], None),
            ("wn_varint_encode_u64", [u64p, i64, u8p], i64),
            ("wn_varint_decode_u64", [u8p, i64, u64p, i64], i64),
            ("wn_merge_topk", [f32p, i64p, i64, i64, i64, f32p, i64p], None),
            ("wn_analyze_batch",
             [u8p, i64p, i64, ctypes.c_int32, i64p, i64p, i64p], i64),
            ("wn_analyze_fetch",
             [u8p, i64p, i64p, i64p, ctypes.POINTER(ctypes.c_uint32), i64p],
             None),
            ("wn_varint_encode_many", [u64p, i64p, i64, u8p, i64p], i64),
        ]:
            fn = getattr(lib, name)
            fn.argtypes = args
            fn.restype = res
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _u64(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.uint64))


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


# ---- sorted uint64 set algebra -------------------------------------------


def intersect_sorted(a, b) -> np.ndarray:
    """Intersection of two ascending unique uint64 arrays."""
    a, b = _u64(a), _u64(b)
    lib = _load()
    if lib is None or min(len(a), len(b)) == 0:
        return np.intersect1d(a, b, assume_unique=True)
    out = np.empty(min(len(a), len(b)), dtype=np.uint64)
    n = lib.wn_intersect_u64(_ptr(a, ctypes.c_uint64), len(a),
                             _ptr(b, ctypes.c_uint64), len(b),
                             _ptr(out, ctypes.c_uint64))
    return out[:n]


def union_sorted(a, b) -> np.ndarray:
    a, b = _u64(a), _u64(b)
    lib = _load()
    if lib is None:
        return np.union1d(a, b)
    out = np.empty(len(a) + len(b), dtype=np.uint64)
    n = lib.wn_union_u64(_ptr(a, ctypes.c_uint64), len(a),
                         _ptr(b, ctypes.c_uint64), len(b),
                         _ptr(out, ctypes.c_uint64))
    return out[:n]


def difference_sorted(a, b) -> np.ndarray:
    """a \\ b for ascending unique uint64 arrays."""
    a, b = _u64(a), _u64(b)
    lib = _load()
    if lib is None or len(a) == 0:
        return np.setdiff1d(a, b, assume_unique=True)
    out = np.empty(len(a), dtype=np.uint64)
    n = lib.wn_difference_u64(_ptr(a, ctypes.c_uint64), len(a),
                              _ptr(b, ctypes.c_uint64), len(b),
                              _ptr(out, ctypes.c_uint64))
    return out[:n]


def membership(vals, allow_sorted) -> np.ndarray:
    """Bool mask: vals[i] >= 0 and vals[i] in allow_sorted (ascending u64).

    The doc-id AllowList test of filtered vector search
    (reference: helpers/allow_list.go consumed in flat/index.go:319)."""
    vals = np.ascontiguousarray(np.asarray(vals, dtype=np.int64))
    allow = _u64(allow_sorted)
    lib = _load()
    if lib is None:
        return (vals >= 0) & np.isin(vals, allow.astype(np.int64))
    out = np.empty(len(vals), dtype=np.uint8)
    lib.wn_membership_i64(_ptr(vals, ctypes.c_int64), len(vals),
                          _ptr(allow, ctypes.c_uint64), len(allow),
                          _ptr(out, ctypes.c_uint8))
    return out.astype(bool)


# ---- varint delta codec ---------------------------------------------------


def _varint_encode_py(vals) -> bytes:
    out = bytearray()
    prev = 0
    for v in vals.tolist():
        d = v - prev
        prev = v
        while d >= 0x80:
            out.append((d & 0x7F) | 0x80)
            d >>= 7
        out.append(d)
    return bytes(out)


def varint_encode(vals) -> bytes:
    """Ascending uint64 -> delta + LEB128 bytes (posting-block codec)."""
    vals = _u64(vals)
    if len(vals) <= 16:
        # the ctypes FFI round-trip costs ~15us — for the tiny bitmaps the
        # inverted index writes per unique value, pure Python wins big
        return _varint_encode_py(vals)
    lib = _load()
    if lib is None:
        return _varint_encode_py(vals)
    out = np.empty(len(vals) * 10 or 1, dtype=np.uint8)
    n = lib.wn_varint_encode_u64(_ptr(vals, ctypes.c_uint64), len(vals),
                                 _ptr(out, ctypes.c_uint8))
    return out[:n].tobytes()


def varint_decode(buf: bytes, count_hint: int | None = None) -> np.ndarray:
    """Decode a varint-delta block. ``count_hint`` is the declared element
    count from the surrounding record; a block holding MORE values than
    declared raises (corrupt/truncated data) rather than over- or
    under-reading — the count field is untrusted on-disk input."""
    lib = None if len(buf) <= 32 else _load()  # FFI overhead > tiny decode
    if lib is None:
        out, prev, d, shift = [], 0, 0, 0
        for byte in buf:
            if shift > 63:
                raise ValueError("corrupt varint block: over-long varint")
            d |= (byte & 0x7F) << shift
            if byte & 0x80:
                shift += 7
            else:
                prev += d
                out.append(prev)
                d, shift = 0, 0
        if count_hint is not None and len(out) != count_hint:
            raise ValueError(
                f"corrupt varint block: {len(out)} values, "
                f"{count_hint} declared")
        return np.asarray(out, dtype=np.uint64)
    arr = np.ascontiguousarray(np.frombuffer(buf, dtype=np.uint8))
    # every value takes >= 1 byte, so len(buf) bounds the count — the
    # declared count is untrusted and must never size an allocation alone
    cap = len(buf) if count_hint is None else min(count_hint, len(buf))
    out = np.empty(max(cap, 1), dtype=np.uint64)
    n = lib.wn_varint_decode_u64(_ptr(arr, ctypes.c_uint8), len(arr),
                                 _ptr(out, ctypes.c_uint64), cap)
    if n < 0:
        raise ValueError("corrupt varint block: over-long varint")
    if count_hint is not None and n != count_hint:
        raise ValueError(
            f"corrupt varint block: {n} values, {count_hint} declared")
    return out[:n]


# ---- cross-shard top-k merge ----------------------------------------------


def merge_topk_host(dists: np.ndarray, ids: np.ndarray, k: int):
    """Merge [L, len] ascending per-shard candidates into global top-k.

    ids < 0 mark dead tail slots. Returns (dists [k] f32, ids [k] i64),
    padded with (3e38, -1). The host half of the scatter-gather reduce
    (reference: index.go:1644-1648) when shards answer over the network
    rather than over ICI."""
    dists = np.ascontiguousarray(np.asarray(dists, dtype=np.float32))
    ids = np.ascontiguousarray(np.asarray(ids, dtype=np.int64))
    if dists.ndim == 1:
        dists, ids = dists[None, :], ids[None, :]
    lib = _load()
    if lib is None:
        flat_d, flat_i = dists.ravel(), ids.ravel()
        live = flat_i >= 0
        flat_d, flat_i = flat_d[live], flat_i[live]
        order = np.argsort(flat_d, kind="stable")[:k]
        out_d = np.full(k, 3.0e38, dtype=np.float32)
        out_i = np.full(k, -1, dtype=np.int64)
        out_d[: len(order)] = flat_d[order]
        out_i[: len(order)] = flat_i[order]
        return out_d, out_i
    out_d = np.empty(k, dtype=np.float32)
    out_i = np.empty(k, dtype=np.int64)
    lib.wn_merge_topk(_ptr(dists, ctypes.c_float), _ptr(ids, ctypes.c_int64),
                      dists.shape[0], dists.shape[1], k,
                      _ptr(out_d, ctypes.c_float), _ptr(out_i, ctypes.c_int64))
    return out_d, out_i


# ---- batch text analyzer --------------------------------------------------

_MODE_BY_TOKENIZATION = {"word": 0, "lowercase": 1, "whitespace": 2,
                         "field": 3}


def analyze_batch(values: list[str], tokenization: str):
    """Tokenize + accumulate a batch of ASCII text values in ONE native
    call (the import hot loop — reference inverted/analyzer.go per put).

    Returns (terms [list of str, sorted], entry_offs [nterms+1],
    entry_rows [E], entry_tfs [E], row_tokens [nrows]) — for each term,
    entries rows/tfs slice [entry_offs[t]:entry_offs[t+1]] give the value
    indices containing it and their term frequencies (rows ascending).
    Returns None when the native library is unavailable (callers fall
    back to the Python tokenizer).
    """
    lib = _load()
    if lib is None:
        return None
    mode = _MODE_BY_TOKENIZATION[tokenization]
    blob = "".join(values).encode("ascii")
    offs = np.zeros(len(values) + 1, dtype=np.int64)
    np.cumsum([len(v) for v in values], out=offs[1:])
    nterms = ctypes.c_int64()
    nentries = ctypes.c_int64()
    termbytes = ctypes.c_int64()
    blob_arr = np.frombuffer(blob, dtype=np.uint8) if blob else \
        np.zeros(1, dtype=np.uint8)
    lib.wn_analyze_batch(
        _ptr(np.ascontiguousarray(blob_arr), ctypes.c_uint8),
        _ptr(offs, ctypes.c_int64), len(values), mode,
        ctypes.byref(nterms), ctypes.byref(nentries), ctypes.byref(termbytes))
    nt, ne, tb = nterms.value, nentries.value, termbytes.value
    terms_blob = np.empty(max(tb, 1), dtype=np.uint8)
    term_offs = np.empty(nt + 1, dtype=np.int64)
    entry_offs = np.empty(nt + 1, dtype=np.int64)
    entry_rows = np.empty(max(ne, 1), dtype=np.int64)
    entry_tfs = np.empty(max(ne, 1), dtype=np.uint32)
    row_tokens = np.empty(max(len(values), 1), dtype=np.int64)
    lib.wn_analyze_fetch(
        _ptr(terms_blob, ctypes.c_uint8), _ptr(term_offs, ctypes.c_int64),
        _ptr(entry_offs, ctypes.c_int64), _ptr(entry_rows, ctypes.c_int64),
        _ptr(entry_tfs, ctypes.c_uint32), _ptr(row_tokens, ctypes.c_int64))
    raw = terms_blob.tobytes()
    terms = [raw[term_offs[t]:term_offs[t + 1]].decode("ascii")
             for t in range(nt)]
    return (terms, entry_offs, entry_rows[:ne], entry_tfs[:ne],
            row_tokens[:len(values)])


def varint_encode_many(arrays: list[np.ndarray]):
    """Encode many ascending-u64 blocks in one call.

    Returns list of bytes per block (Python fallback when no native lib).
    """
    lib = _load()
    if lib is None or not arrays:
        return [varint_encode(a) for a in arrays]
    concat = np.concatenate([_u64(a) for a in arrays]) if arrays else \
        np.empty(0, np.uint64)
    offs = np.zeros(len(arrays) + 1, dtype=np.int64)
    np.cumsum([len(a) for a in arrays], out=offs[1:])
    out = np.empty(max(int(offs[-1]) * 10, 1), dtype=np.uint8)
    lens = np.empty(len(arrays), dtype=np.int64)
    total = lib.wn_varint_encode_many(
        _ptr(np.ascontiguousarray(concat) if len(concat) else
             np.zeros(1, np.uint64), ctypes.c_uint64),
        _ptr(offs, ctypes.c_int64), len(arrays),
        _ptr(out, ctypes.c_uint8), _ptr(lens, ctypes.c_int64))
    blob = out[:total].tobytes()
    res = []
    pos = 0
    for n in lens.tolist():
        res.append(blob[pos:pos + n])
        pos += n
    return res
