"""ctypes bindings for the native gRPC data plane (csrc/dataplane.cpp).

The Python gRPC fabric caps the server at ~1.2k QPS on one core
(BASELINE r4); the native plane moves transport + fast-path Search
parsing + batch coalescing + reply building into C++ over the system
libnghttp2 and hands Python one coalesced device dispatch per batch plus
raw request bytes for everything else. ``available()`` is False when the
shared library (or libnghttp2) is absent — the Python gRPC server is the
fallback, and stays the default unless WEAVIATE_TPU_NATIVE_DATAPLANE=1.
"""

from __future__ import annotations

import ctypes
import os
import threading
from dataclasses import dataclass

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libwvdataplane.so")
_SRC_DIR = os.path.abspath(os.path.join(_HERE, os.pardir, os.pardir, "csrc"))

_lib = None
_tried = False
_lock = threading.Lock()


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        from weaviate_tpu.native import build_and_load

        lib = build_and_load(os.path.join(_SRC_DIR, "dataplane.cpp"), _SO,
                             link=["-l:libnghttp2.so.14", "-lpthread"])
        if lib is None:
            return None
        i32, i64 = ctypes.c_int32, ctypes.c_int64
        u64 = ctypes.c_uint64
        i64p = ctypes.POINTER(i64)
        u64p = ctypes.POINTER(u64)
        i32p = ctypes.POINTER(i32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f32p = ctypes.POINTER(ctypes.c_float)
        for name, args, res in [
            ("dp_start", [i32, i32, i32], i32),
            ("dp_stop", [], None),
            ("dp_register_collection", [ctypes.c_char_p, i32], i32),
            ("dp_cache_put", [i32, i64, i64p, u8p, u8p, i64p], None),
            ("dp_cache_clear", [i32], None),
            ("dp_wait",
             [i32, i32p, i64p, u64p, i32p, f32p, u64p, ctypes.c_char_p,
              i32, i64p], i32),
            ("dp_fallback_payload", [u64, u8p], None),
            ("dp_post_raw", [u64, u8p, i64, i32, ctypes.c_char_p], None),
            ("dp_post_batch",
             [i32, i64, u64p, i32p, i64, i64p, f32p, i64p, ctypes.c_float,
              u64p], i64),
            ("dp_stats", [u64p, u64p], None),
            ("dp_cache_stats", [i32, i64p, u64p, u64p], None),
            ("dp_bench",
             [i32, i32, i32, i32, i32, u8p, i64, ctypes.POINTER(
                 ctypes.c_double), f32p, f32p, f32p, i64p], i64),
        ]:
            fn = getattr(lib, name)
            fn.argtypes = args
            fn.restype = res
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray, t):
    return a.ctypes.data_as(ctypes.POINTER(t))


@dataclass
class SearchBatch:
    coll_id: int
    tokens: np.ndarray   # uint64 [n]
    ks: np.ndarray       # int32 [n]
    queries: np.ndarray  # float32 [n, dim]


@dataclass
class FallbackRequest:
    token: int
    method: str
    payload: bytes


class DataPlane:
    """One process-wide native data plane instance."""

    MAX_BATCH = 128

    def __init__(self, port: int = 0, max_batch: int = 0,
                 window_us: int = 0, max_dim: int = 4096):
        lib = _load()
        if lib is None:
            raise RuntimeError("native data plane unavailable")
        self._lib = lib
        self.max_batch = max_batch or self.MAX_BATCH
        self.max_dim = max_dim
        p = lib.dp_start(port, self.max_batch, window_us)
        if p < 0:
            raise OSError(-p, "dp_start failed")
        self.port = int(p)
        self._dims: dict[int, int] = {}
        # reusable dp_wait buffers (one waiter thread)
        self._tokens = np.empty(self.max_batch, np.uint64)
        self._ks = np.empty(self.max_batch, np.int32)
        self._qbuf = np.empty(self.max_batch * max_dim, np.float32)

    def stop(self):
        self._lib.dp_stop()

    def register_collection(self, name: str, dim: int) -> int:
        if dim <= 0 or dim > self.max_dim:
            # the dp_wait query buffer is sized max_batch*max_dim —
            # larger dims must stay on the fallback path
            return -1
        cid = self._lib.dp_register_collection(name.encode(), int(dim))
        if cid >= 0:
            self._dims[cid] = int(dim)
        return cid

    def cache_put(self, coll_id: int, doc_ids, uuids: list[str],
                  props: list[bytes]):
        doc_ids = np.ascontiguousarray(doc_ids, dtype=np.int64)
        ub = "".join(uuids).encode("ascii")
        assert len(ub) == 36 * len(doc_ids)
        ua = np.frombuffer(ub, np.uint8)
        blob = b"".join(props)
        poffs = np.zeros(len(props) + 1, np.int64)
        np.cumsum([len(p) for p in props], out=poffs[1:])
        pa = np.frombuffer(blob, np.uint8) if blob else np.zeros(1, np.uint8)
        self._lib.dp_cache_put(coll_id, len(doc_ids),
                               _ptr(doc_ids, ctypes.c_int64),
                               _ptr(ua, ctypes.c_uint8),
                               _ptr(pa, ctypes.c_uint8),
                               _ptr(poffs, ctypes.c_int64))

    def wait(self, timeout_ms: int = 200):
        """None (timeout) | SearchBatch | FallbackRequest | 'stopped'."""
        coll = ctypes.c_int32(0)
        count = ctypes.c_int64(0)
        token = ctypes.c_uint64(0)
        plen = ctypes.c_int64(0)
        mbuf = ctypes.create_string_buffer(256)
        kind = self._lib.dp_wait(
            timeout_ms, ctypes.byref(coll), ctypes.byref(count),
            _ptr(self._tokens, ctypes.c_uint64),
            _ptr(self._ks, ctypes.c_int32),
            _ptr(self._qbuf, ctypes.c_float), ctypes.byref(token), mbuf,
            256, ctypes.byref(plen))
        if kind == 0:
            return None
        if kind == 3:
            return "stopped"
        if kind == 1:
            n = count.value
            dim = self._dims.get(coll.value, 0)
            return SearchBatch(
                coll_id=coll.value, tokens=self._tokens[:n].copy(),
                ks=self._ks[:n].copy(),
                queries=self._qbuf[:n * dim].reshape(n, dim).copy())
        payload = np.empty(max(plen.value, 1), np.uint8)
        self._lib.dp_fallback_payload(token.value,
                                      _ptr(payload, ctypes.c_uint8))
        return FallbackRequest(token=token.value,
                               method=mbuf.value.decode(),
                               payload=payload[:plen.value].tobytes())

    def post_raw(self, token: int, reply: bytes, status: int = 0,
                 message: str = ""):
        buf = np.frombuffer(reply, np.uint8) if reply else \
            np.zeros(1, np.uint8)
        self._lib.dp_post_raw(ctypes.c_uint64(token),
                              _ptr(buf, ctypes.c_uint8), len(reply),
                              status, message.encode() or None)

    def post_batch(self, batch: SearchBatch, ids: np.ndarray,
                   dists: np.ndarray, counts: np.ndarray,
                   took_s: float) -> np.ndarray:
        """Returns tokens the C++ side could not serve (cache misses)."""
        n, kmax = ids.shape
        ids = np.ascontiguousarray(ids, np.int64)
        dists = np.ascontiguousarray(dists, np.float32)
        counts = np.ascontiguousarray(counts, np.int64)
        miss = np.empty(n, np.uint64)
        tokens = np.ascontiguousarray(batch.tokens, np.uint64)
        ks = np.ascontiguousarray(batch.ks, np.int32)
        nm = self._lib.dp_post_batch(
            batch.coll_id, n, _ptr(tokens, ctypes.c_uint64),
            _ptr(ks, ctypes.c_int32), kmax, _ptr(ids, ctypes.c_int64),
            _ptr(dists, ctypes.c_float), _ptr(counts, ctypes.c_int64),
            ctypes.c_float(took_s), _ptr(miss, ctypes.c_uint64))
        return miss[:nm].copy()

    def stats(self) -> tuple[int, int]:
        fast = ctypes.c_uint64(0)
        fb = ctypes.c_uint64(0)
        self._lib.dp_stats(ctypes.byref(fast), ctypes.byref(fb))
        return fast.value, fb.value

    def cache_stats(self, coll_id: int = -1) -> dict:
        """Reply-cache accounting: cached doc entries for ``coll_id``
        (-1 = all collections) plus global per-doc hit/miss counts from
        the C++ reply builder — ``misses == 0`` after a warm pass means
        property fetch on the hot path never re-entered Python."""
        entries = ctypes.c_int64(0)
        hits = ctypes.c_uint64(0)
        misses = ctypes.c_uint64(0)
        self._lib.dp_cache_stats(coll_id, ctypes.byref(entries),
                                 ctypes.byref(hits), ctypes.byref(misses))
        return {"entries": entries.value, "hits": hits.value,
                "misses": misses.value}


def bench(port: int, conns: int, streams: int, duration_ms: int, dim: int,
          request_head: bytes) -> dict:
    """Native load generator against a Search endpoint (ours or any
    gRPC server speaking the same proto). ``request_head``: serialized
    SearchRequest WITHOUT near_vector (collection/limit/metadata/flags)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native data plane unavailable")
    head = np.frombuffer(request_head, np.uint8)
    qps = ctypes.c_double(0)
    p50 = ctypes.c_float(0)
    p95 = ctypes.c_float(0)
    p99 = ctypes.c_float(0)
    errors = ctypes.c_int64(0)
    done = lib.dp_bench(port, conns, streams, duration_ms, dim,
                        _ptr(head, ctypes.c_uint8), len(request_head),
                        ctypes.byref(qps), ctypes.byref(p50),
                        ctypes.byref(p95), ctypes.byref(p99),
                        ctypes.byref(errors))
    return {"done": int(done), "qps": qps.value, "p50_ms": p50.value,
            "p95_ms": p95.value, "p99_ms": p99.value,
            "errors": int(errors.value)}
