"""Server configuration from the environment.

Reference: usecases/config/environment.go (747 lines of env parsing) +
config_handler.go (yaml/json file) + go-flags. The same env surface is
honored here so a reference deployment's environment carries over;
``ServerConfig.from_env`` is the single entry point, with an optional
json/yaml config file via CONFIG_FILE (reference: --config-file flag).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


def _flag(env, name: str, default: bool = False) -> bool:
    raw = env.get(name)
    if raw is None:
        return default
    return raw.lower() in ("true", "1", "on", "enabled")


def _csv(env, name: str) -> list[str]:
    return [s.strip() for s in env.get(name, "").split(",") if s.strip()]


def _int(env, name: str, default: int) -> int:
    raw = env.get(name)
    try:
        return int(raw) if raw else default
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}")


def _float(env, name: str, default: float) -> float:
    raw = env.get(name)
    try:
        return float(raw) if raw else default
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}")


def _fraction(env, name: str, default: float) -> float:
    raw = env.get(name)
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}")
    if not 0.0 < v <= 1.0:
        raise ValueError(f"{name} must be in (0, 1], got {v}")
    return v


@dataclass
class ServerConfig:
    # persistence (PERSISTENCE_DATA_PATH, environment.go)
    data_path: str = "./data"
    # PERSISTENCE_WAL_SYNC: fsync every WAL append before acking the
    # write (durability over throughput — see bench.py durability_tax
    # for the cost). Off = the OS page cache decides when acked writes
    # hit disk, so a POWER failure (not a process crash) can lose the
    # tail. The raft bucket is pinned sync regardless (cluster/node.py).
    wal_sync: bool = False
    # API listeners
    host: str = "127.0.0.1"
    rest_port: int = 8080
    grpc_port: int = 50051
    # query defaults (QUERY_DEFAULTS_LIMIT / QUERY_MAXIMUM_RESULTS)
    query_defaults_limit: int = 25
    query_maximum_results: int = 10_000
    # modules (ENABLE_MODULES / DEFAULT_VECTORIZER_MODULE)
    enabled_modules: list[str] | None = None
    default_vectorizer_module: str = "none"
    # cluster (CLUSTER_HOSTNAME / RAFT_JOIN / CLUSTER_JOIN ...)
    cluster_advertise: str = ""
    cluster_hostname: str = "node-0"
    raft_join: list[str] = field(default_factory=list)
    cluster_join: list[str] = field(default_factory=list)
    cluster_data_port: int = 0
    # features
    async_indexing: bool = False
    auto_schema_enabled: bool = True
    # observability
    prometheus_enabled: bool = False
    prometheus_port: int = 2112
    # tailboard (always-on latency attribution): the per-request phase
    # timeline can be disabled wholesale (bench A/B, emergencies); SLO
    # objectives are a JSON list (WEAVIATE_TPU_SLO) overriding the
    # built-in availability/latency defaults — see runtime/tailboard.py
    tailboard_enabled: bool = True
    slo_config: str = ""
    profiling_port: int = 0  # 0 = profiler server off (PROFILING_PORT)
    # kernelscope: how many /v1/debug/profile?ms=N captures to keep
    # persisted under <data_dir>/kernelscope (PROFILING_KEEP)
    profile_keep: int = 8
    # driftwatch: online recall/perf drift plane (canary probes + live
    # telemetry vs benchkeeper bands) on a cyclemanager period
    driftwatch_enabled: bool = True
    drift_interval_s: float = 30.0
    log_level: str = "info"
    log_format: str = "text"
    disable_telemetry: bool = False
    # resources (GOMEMLIMIT analog: device + host budgets for memwatch)
    memory_limit_bytes: int = 0  # 0 = unlimited
    # HBM admission control (runtime/memwatch.py watermark gating):
    # imports are refused with 507 past high*budget and accepted again
    # under low*budget (hysteresis). The budget comes from allocator
    # stats where available, else HBM_DEVICE_LIMIT_BYTES.
    hbm_device_limit_bytes: int = 0  # 0 = allocator-reported / unlimited
    hbm_high_watermark: float = 0.9
    hbm_low_watermark: float = 0.8
    # failure policy (runtime/retry.py + cluster/transport.py):
    # remote_rpc_timeout_s replaces cluster/remote.py's hard-coded 30s
    # per-attempt ceiling; query_deadline_s is the default request time
    # budget opened at the REST edge (0 = none unless the client sends
    # X-Request-Timeout), propagated down through the batcher, shard
    # fan-out and every transport call
    remote_rpc_timeout_s: float = 30.0
    query_deadline_s: float = 0.0
    # backups
    backup_filesystem_path: str = ""

    @classmethod
    def from_env(cls, env=None) -> "ServerConfig":
        env = os.environ if env is None else env
        cfg = cls(
            data_path=env.get("PERSISTENCE_DATA_PATH", "./data"),
            wal_sync=_flag(env, "PERSISTENCE_WAL_SYNC"),
            host=env.get("BIND_ADDRESS", env.get("ORIGIN_HOST",
                                                 "127.0.0.1")),
            rest_port=_int(env, "PORT", 8080),
            grpc_port=_int(env, "GRPC_PORT", 50051),
            query_defaults_limit=_int(env, "QUERY_DEFAULTS_LIMIT", 25),
            query_maximum_results=_int(env, "QUERY_MAXIMUM_RESULTS", 10_000),
            enabled_modules=_csv(env, "ENABLE_MODULES") or None,
            default_vectorizer_module=env.get(
                "DEFAULT_VECTORIZER_MODULE", "none"),
            cluster_hostname=env.get("CLUSTER_HOSTNAME", "node-0"),
            raft_join=_csv(env, "RAFT_JOIN"),
            cluster_join=_csv(env, "CLUSTER_JOIN"),
            cluster_data_port=_int(env, "CLUSTER_DATA_BIND_PORT", 0),
            cluster_advertise=env.get("CLUSTER_ADVERTISE_ADDR", ""),
            async_indexing=_flag(env, "ASYNC_INDEXING"),
            auto_schema_enabled=_flag(env, "AUTOSCHEMA_ENABLED", True),
            prometheus_enabled=_flag(env, "PROMETHEUS_MONITORING_ENABLED"),
            prometheus_port=_int(env, "PROMETHEUS_MONITORING_PORT", 2112),
            tailboard_enabled=_flag(env, "WEAVIATE_TPU_TAILBOARD", True),
            slo_config=env.get("WEAVIATE_TPU_SLO", ""),
            profiling_port=_int(env, "PROFILING_PORT", 0),
            profile_keep=_int(env, "PROFILING_KEEP", 8),
            driftwatch_enabled=_flag(env, "WEAVIATE_TPU_DRIFTWATCH", True),
            drift_interval_s=_float(env, "WEAVIATE_TPU_DRIFT_INTERVAL_S",
                                    30.0),
            log_level=env.get("LOG_LEVEL", "info"),
            log_format=env.get("LOG_FORMAT", "text"),
            disable_telemetry=_flag(env, "DISABLE_TELEMETRY"),
            memory_limit_bytes=_int(env, "MEMORY_LIMIT_BYTES", 0),
            hbm_device_limit_bytes=_int(env, "HBM_DEVICE_LIMIT_BYTES", 0),
            hbm_high_watermark=_fraction(env, "HBM_HIGH_WATERMARK", 0.9),
            hbm_low_watermark=_fraction(env, "HBM_LOW_WATERMARK", 0.8),
            remote_rpc_timeout_s=_float(env, "REMOTE_RPC_TIMEOUT_S", 30.0),
            query_deadline_s=_float(env, "QUERY_DEADLINE_S", 0.0),
            backup_filesystem_path=env.get("BACKUP_FILESYSTEM_PATH", ""),
        )
        path = env.get("CONFIG_FILE", "")
        if path:
            cfg = cfg.merge_file(path)
        return cfg

    def merge_file(self, path: str) -> "ServerConfig":
        """Overlay a json (or flat yaml subset) config file — file values
        win over env, matching the reference's precedence for
        --config-file."""
        with open(path) as f:
            raw = f.read()
        try:
            data = json.loads(raw)
        except json.JSONDecodeError:
            # minimal yaml: "key: value" lines (the reference accepts
            # yaml; full yaml needs no dependency for flat files)
            data = {}
            for line in raw.splitlines():
                line = line.split("#", 1)[0].strip()
                if ":" in line:
                    k, _, v = line.partition(":")
                    data[k.strip()] = v.strip()
        out = ServerConfig(**{**self.__dict__})
        for k, v in data.items():
            key = k.replace("-", "_")
            if hasattr(out, key):
                cur = getattr(out, key)
                if isinstance(cur, bool):
                    v = str(v).lower() in ("true", "1", "on")
                elif isinstance(cur, int):
                    v = int(v)
                elif isinstance(cur, float):
                    v = float(v)
                elif isinstance(cur, list) and isinstance(v, str):
                    v = [s.strip() for s in v.split(",") if s.strip()]
                setattr(out, key, v)
        return out
