"""Merkle hashtree over a shard's object digests.

Reference: usecases/replica/hashtree/ (plain/compact/segmented trees,
diff readers). Leaves are 2^depth buckets keyed by uuid hash; a leaf's
hash is the XOR of its entry hashes (order-independent, incrementally
mergeable), inner nodes hash their children. Two replicas walk the tree
top-down exchanging node hashes to find the leaf ranges that differ,
then reconcile only those buckets' entries.
"""

from __future__ import annotations

import hashlib

import xxhash

HASH_LEN = 16


def entry_hash(uuid: str, mtime: int, deleted: bool, content_hash: bytes) -> bytes:
    h = hashlib.sha256()
    h.update(uuid.encode())
    h.update(mtime.to_bytes(8, "little"))
    h.update(b"D" if deleted else b"L")
    h.update(content_hash)
    return h.digest()[:HASH_LEN]


def digest_rank(d: dict) -> tuple:
    """Total order over replica digests: newest mtime wins; at equal
    mtime a tombstone beats an object; at a full tie the content hash
    breaks it DETERMINISTICALLY — both sides of a conflict order the
    same way, so same-millisecond divergent writes still converge
    instead of re-diffing forever."""
    return (d["mtime"], 1 if d["deleted"] else 0, d["hash"])


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


class MerkleTree:
    """levels[0] = root ... levels[depth] = leaves (2^depth buckets)."""

    def __init__(self, depth: int = 8):
        self.depth = depth
        self.n_leaves = 1 << depth
        self.leaves = [bytes(HASH_LEN)] * self.n_leaves
        self._levels: list[list[bytes]] | None = None

    @staticmethod
    def bucket_of(uuid: str, depth: int) -> int:
        return xxhash.xxh64_intdigest(uuid) % (1 << depth)

    def insert(self, uuid: str, mtime: int, deleted: bool,
               content_hash: bytes) -> None:
        b = self.bucket_of(uuid, self.depth)
        self.leaves[b] = _xor(self.leaves[b],
                              entry_hash(uuid, mtime, deleted, content_hash))
        self._levels = None

    def _build(self) -> list[list[bytes]]:
        if self._levels is None:
            levels = [self.leaves]
            cur = self.leaves
            while len(cur) > 1:
                nxt = []
                for i in range(0, len(cur), 2):
                    h = hashlib.sha256()
                    h.update(cur[i])
                    h.update(cur[i + 1])
                    nxt.append(h.digest()[:HASH_LEN])
                levels.append(nxt)
                cur = nxt
            levels.reverse()  # [root ... leaves]
            self._levels = levels
        return self._levels

    @property
    def root(self) -> bytes:
        return self._build()[0][0]

    def level_hashes(self, level: int, positions: list[int]) -> list[bytes]:
        lv = self._build()[level]
        return [lv[p] for p in positions]

    def diff_buckets(self, peer_level_fn) -> list[int]:
        """Walk down against a peer; returns differing leaf buckets.

        ``peer_level_fn(level, positions) -> list[bytes]`` returns the
        peer's node hashes (the RPC). Exchange volume is O(diff * depth),
        the point of the reference's hashtree sync.
        """
        candidates = [0]
        if peer_level_fn(0, [0])[0] == self.root:
            return []
        for level in range(1, self.depth + 1):
            children = [c for p in candidates for c in (2 * p, 2 * p + 1)]
            mine = self.level_hashes(level, children)
            theirs = peer_level_fn(level, children)
            candidates = [c for c, m, t in zip(children, mine, theirs)
                          if m != t]
            if not candidates:
                return []
        return candidates
