"""Replication: synchronous 2PC writes + async Merkle anti-entropy.

Reference: usecases/replica/ — Replicator/coordinator (2PC,
coordinator.go:69,132,158), consistency levels (config.go), Finder reads
with digest comparison + read repair (repairer.go), hashtree/
(Merkle trees) + shard_hashbeater.go (background diff + propagation).
"""

from weaviate_tpu.replication.finder import Finder
from weaviate_tpu.replication.hashbeater import HashBeater
from weaviate_tpu.replication.hashtree import MerkleTree
from weaviate_tpu.replication.replicator import (
    ConsistencyError,
    Replicator,
    register_replication,
    required_acks,
)

__all__ = [
    "Finder",
    "HashBeater",
    "MerkleTree",
    "ConsistencyError",
    "Replicator",
    "register_replication",
    "required_acks",
]
