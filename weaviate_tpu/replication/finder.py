"""Consistent reads with digest comparison + read repair.

Reference: usecases/replica coordinator.go:178 (Finder.Pull): fetch the
full object from one replica and digests from the others, compare, and
if replicas disagree return the newest version and push it to the stale
replicas (repairer.go).
"""

from __future__ import annotations

import logging

from weaviate_tpu.cluster.transport import RpcError, rpc
from weaviate_tpu.replication.replicator import ConsistencyError, required_acks
from weaviate_tpu.runtime import degrade, tracing
from weaviate_tpu.storage.objects import StorageObject

logger = logging.getLogger(__name__)


class Finder:
    def __init__(self, collection):
        self.col = collection

    def _digest(self, node: str, shard_name: str, uuid: str) -> dict | None:
        if node == self.col.local_node:
            return self.col._load_shard(shard_name).object_digest(uuid)
        remote = self.col._require_remote(shard_name)
        return rpc(remote.resolver(node),
                   f"/replicas/{self.col.config.name}/{shard_name}/digest",
                   {"uuid": uuid}, timeout=remote.timeout).get("digest")

    def _fetch(self, node: str, shard_name: str, uuid: str) -> bytes | None:
        if node == self.col.local_node:
            return self.col._load_shard(shard_name).objects.get(uuid.encode())
        remote = self.col._require_remote(shard_name)
        return rpc(remote.resolver(node),
                   f"/replicas/{self.col.config.name}/{shard_name}/objects:fetch",
                   {"uuids": [uuid]}, timeout=remote.timeout)["objects"][0]

    def _repair(self, node: str, shard_name: str, raw: bytes | None,
                delete: dict | None) -> None:
        try:
            if node == self.col.local_node:
                self.col._load_shard(shard_name).apply_sync(
                    [raw] if raw else [], [delete] if delete else [])
                return
            remote = self.col._require_remote(shard_name)
            rpc(remote.resolver(node),
                f"/replicas/{self.col.config.name}/{shard_name}/sync:apply",
                {"objects": [raw] if raw else [],
                 "deletes": [delete] if delete else []},
                timeout=remote.timeout)
        except Exception:
            # best-effort side effect: a failed repair (unreachable peer,
            # local validation error) must not fail the read itself
            logger.warning("read repair push to %s/%s failed", node,
                           shard_name, exc_info=True)

    def get_object(self, uuid: str, shard_name: str,
                   level: str = "QUORUM") -> StorageObject | None:
        """Read at a consistency level; repairs stale replicas as a side
        effect (reference: Finder.Pull + repairer)."""
        with tracing.span("replication.read", shard=shard_name,
                          level=level):
            return self._get_object(uuid, shard_name, level)

    def _get_object(self, uuid: str, shard_name: str,
                    level: str) -> StorageObject | None:
        nodes = self.col.sharding.nodes_for(shard_name)
        need = required_acks(level, len(nodes))
        digests: dict[str, dict | None] = {}
        errors = []
        for node in nodes:
            if len(digests) >= need and level != "ALL":
                # enough replicas answered for the level — but keep going
                # only if we still need votes
                break
            try:
                digests[node] = self._digest(node, shard_name, uuid)
            except (RpcError, KeyError) as e:
                errors.append(f"{node}: {e}")
        if len(digests) < need:
            # degraded read (ONE/QUORUM): the level is unreachable but
            # SOME replica answered — serve its best-known value with an
            # explicit downgraded-consistency marker rather than failing
            # the whole read. ALL stays strict: the caller demanded
            # every replica by name and gets the typed error.
            if digests and level != "ALL":
                degrade.report(
                    "consistency_downgraded",
                    collection=self.col.config.name, shard=shard_name,
                    detail=f"{len(digests)}/{len(nodes)} replicas "
                           f"answered, need {need} for {level}: "
                           f"{'; '.join(errors)}")
            else:
                raise ConsistencyError(
                    f"{len(digests)}/{len(nodes)} replicas answered, need "
                    f"{need} for {level}: {'; '.join(errors)}")

        # winner by digest_rank: newest mtime, tombstone beats object at
        # a tie, content hash as the deterministic tie-break
        from weaviate_tpu.replication.hashtree import digest_rank

        seen = {n: d for n, d in digests.items() if d is not None}
        if not seen:
            return None
        winner_node, winner = max(seen.items(),
                                  key=lambda kv: digest_rank(kv[1]))

        stale = [n for n, d in digests.items()
                 if d is None or digest_rank(d) < digest_rank(winner)]
        if stale:
            # read-path divergence signal: a consistency-level read just
            # caught replicas disagreeing between anti-entropy beats —
            # feeds /v1/debug/replication alongside the beat stats
            from weaviate_tpu.replication.hashbeater import (
                replication_status)

            replication_status.record_read_divergence(
                self.col.config.name, shard_name, len(stale))

        if winner["deleted"]:
            for node in stale:
                self._repair(node, shard_name, None,
                             {"uuid": uuid, "mtime": winner["mtime"]})
            return None
        raw = None
        # the winner can die between digest and fetch: fail over to the
        # remaining answering replicas (rank order) with a staleness
        # marker instead of failing the read
        candidates = sorted(seen, key=lambda n: digest_rank(seen[n]),
                            reverse=True)
        for i, node in enumerate(candidates):
            try:
                raw = self._fetch(node, shard_name, uuid)
            except RpcError as e:
                if i == len(candidates) - 1:
                    # EVERY answering replica failed the fetch: this is
                    # unavailability, not nonexistence — the digests just
                    # proved the object exists. A degraded read may
                    # downgrade consistency; it must never invent a 404
                    # (a caller doing read-then-recreate would clobber
                    # the surviving copies).
                    raise ConsistencyError(
                        f"object fetch failed on every answering replica "
                        f"({', '.join(candidates)}) for {uuid}: {e}") from e
                degrade.report("missing_replica",
                               collection=self.col.config.name,
                               shard=shard_name, node=node,
                               detail=f"fetch failed: {e}")
                continue
            if node != winner_node:
                stale = [n for n in stale if n != node]
            break
        if raw is None:
            return None
        if stale:
            logger.info("read repair: %s stale for %s", stale, uuid)
            for node in stale:
                self._repair(node, shard_name, raw, None)
        return StorageObject.from_bytes(raw)
