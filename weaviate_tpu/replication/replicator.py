"""2PC write replication with consistency levels.

Reference: usecases/replica/replicator.go:57 + coordinator.go — the
coordinator broadcasts "prepare" to every replica (coordinator.go:69
broadcast), counts acks against the consistency level (config.go
ONE/QUORUM/ALL), then commits (commitAll :132, Push :158); failed
prepares trigger aborts. The intra-cluster endpoints live beside the
shard data plane (clusterapi /replicas/...).
"""

from __future__ import annotations

import logging
import threading
import uuid as uuid_mod

from weaviate_tpu.cluster.transport import RpcError, rpc
from weaviate_tpu.runtime import faultline, tracing
from weaviate_tpu.storage.objects import StorageObject

logger = logging.getLogger(__name__)

LEVELS = ("ONE", "QUORUM", "ALL")


class ConsistencyError(RuntimeError):
    pass


def required_acks(level: str, n_replicas: int) -> int:
    if level == "ONE":
        return 1
    if level == "QUORUM":
        return n_replicas // 2 + 1
    if level == "ALL":
        return n_replicas
    raise ValueError(f"unknown consistency level {level!r}; "
                     f"expected one of {LEVELS}")


class Replicator:
    """Write coordinator for one collection (reference Replicator)."""

    def __init__(self, collection):
        self.col = collection

    # -- replica RPC primitives (local replicas short-circuit HTTP) ---------

    def _shard_local(self, shard_name: str):
        return self.col._load_shard(shard_name)

    def _prepare(self, node: str, shard_name: str, rid: str, task: tuple) -> None:
        # faultline: fires for LOCAL replicas too — a coordinator whose
        # own replica faults mid-prepare must abort like any other
        faultline.fire("replication.prepare", node=node, shard=shard_name)
        if node == self.col.local_node:
            self._shard_local(shard_name).stage(rid, task)
            return
        kind = task[0]
        payload = {"request_id": rid, "kind": kind}
        if kind == "put":
            payload["objects"] = [o.to_bytes() for o in task[1]]
        else:
            payload["uuid"], payload["tombstone_ms"] = task[1], task[2]
        self._rpc(node, shard_name, "prepare", payload)

    def _commit(self, node: str, shard_name: str, rid: str):
        faultline.fire("replication.commit", node=node, shard=shard_name)
        if node == self.col.local_node:
            return self._shard_local(shard_name).commit_staged(rid)
        # unwrap so local and remote commits return the same shape
        return self._rpc(node, shard_name, "commit",
                         {"request_id": rid}).get("result")

    def _abort(self, node: str, shard_name: str, rid: str) -> None:
        try:
            if node == self.col.local_node:
                self._shard_local(shard_name).abort_staged(rid)
            else:
                self._rpc(node, shard_name, "abort", {"request_id": rid})
        except Exception:
            logger.warning("abort failed on %s/%s", node, shard_name)

    def _rpc(self, node: str, shard_name: str, op: str, payload: dict):
        remote = self.col._require_remote(shard_name)
        return rpc(remote.resolver(node),
                   f"/replicas/{self.col.config.name}/{shard_name}/{op}",
                   payload, timeout=remote.timeout)

    # -- coordinator (reference coordinator.go Push) --------------------------

    def _two_phase(self, shard_name: str, task: tuple, level: str) -> list:
        """Returns the per-replica commit results (callers aggregate).

        Catches ALL exceptions per replica — a commit-time validation or
        memory error on one replica must still commit/abort the others,
        or their staged entries leak and the set diverges silently."""
        from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

        nodes = self.col.sharding.nodes_for(shard_name)
        need = required_acks(level, len(nodes))
        rid = str(uuid_mod.uuid4())

        # Both phases broadcast CONCURRENTLY and return as soon as `need`
        # acks land (reference coordinator.broadcast + level counting,
        # coordinator.go:96-130): one partitioned replica hanging until its
        # RPC timeout must not add that timeout to a write that already has
        # quorum. Stragglers finish on pool threads after we return —
        # successes are committed so they converge, failures are aborted
        # (and any leaked staged entry falls to the gc_staged TTL +
        # anti-entropy).
        pool = ThreadPoolExecutor(max_workers=max(1, 2 * len(nodes)))

        def safe_abort(node):
            try:
                self._abort(node, shard_name, rid)
            except Exception:
                pass  # unreachable abort → staged-entry TTL cleans up

        def commit_straggler(fut, node):
            if fut.exception() is None:
                try:
                    self._commit(node, shard_name, rid)
                except Exception:
                    safe_abort(node)

        try:
            # tracing.propagate: the broadcast runs on pool threads, and
            # each replica RPC must carry this request's traceparent so
            # the write yields one stitched trace
            with tracing.span("replication.prepare", shard=shard_name,
                              replicas=len(nodes), need=need):
                prep_futs = {pool.submit(tracing.propagate(self._prepare),
                                         node, shard_name, rid,
                                         task): node for node in nodes}
                prepared: list[str] = []
                errors: list[str] = []
                pending = set(prep_futs)
                while pending and len(prepared) < need \
                        and len(errors) <= len(nodes) - need:
                    done, pending = wait(pending,
                                         return_when=FIRST_COMPLETED)
                    for f in done:
                        node = prep_futs[f]
                        if f.exception() is None:
                            prepared.append(node)
                        else:
                            errors.append(f"{node}: {f.exception()}")
            from weaviate_tpu.runtime.metrics import replication_phase_total

            replication_phase_total.labels(
                "prepare", "ok" if len(prepared) >= need else "failed").inc()
            if len(prepared) < need:
                # quorum impossible: abort what prepared; late preparers
                # abort themselves via callback
                for f in pending:
                    node = prep_futs[f]
                    f.add_done_callback(
                        lambda fut, n=node: fut.exception() is None
                        and safe_abort(n))
                for node in prepared:
                    pool.submit(safe_abort, node)
                raise ConsistencyError(
                    f"prepare acked by {len(prepared)}/{len(nodes)} replicas, "
                    f"need {need} for {level}: {'; '.join(errors)}")
            # quorum prepared; late preparers get committed as they arrive
            for f in pending:
                f.add_done_callback(
                    lambda fut, n=prep_futs[f]: commit_straggler(fut, n))
            # commit phase over the quorum set

            with tracing.span("replication.commit", shard=shard_name,
                              replicas=len(prepared), need=need):
                commit_futs = {pool.submit(tracing.propagate(self._commit),
                                           node, shard_name, rid):
                               node for node in prepared}
                results: list = []
                commit_errors: list[str] = []
                pending = set(commit_futs)
                while pending and len(results) < need:
                    done, pending = wait(pending,
                                         return_when=FIRST_COMPLETED)
                    for f in done:
                        node = commit_futs[f]
                        if f.exception() is None:
                            results.append(f.result())
                        else:
                            commit_errors.append(
                                f"{node}: {f.exception()}")
                            # release any still-staged entry (idempotent
                            # if the commit half-landed or the node is
                            # unreachable)
                            pool.submit(safe_abort, node)
            for f in pending:  # commit stragglers: abort on failure
                node = commit_futs[f]
                f.add_done_callback(
                    lambda fut, n=node: fut.exception() is not None
                    and safe_abort(n))
            replication_phase_total.labels(
                "commit", "ok" if len(results) >= need else "failed").inc()
            if len(results) < need:
                raise ConsistencyError(
                    f"commit acked by {len(results)}/{len(prepared)} prepared "
                    f"replicas, need {need}: {'; '.join(commit_errors)}")
            return results
        finally:
            pool.shutdown(wait=False)

    def put_objects(self, shard_name: str, objs: list[StorageObject],
                    level: str = "QUORUM"):
        results = self._two_phase(shard_name, ("put", objs), level)
        return results[0] if results else None

    def delete(self, shard_name: str, uuid: str, level: str = "QUORUM",
               tombstone_ms: int | None = None) -> bool:
        import time as _time

        ts = tombstone_ms or int(_time.time() * 1000)
        results = self._two_phase(shard_name, ("delete", uuid, ts), level)
        # deleted anywhere = deleted (a replica that missed the put and
        # reports False is simply stale, not authoritative)
        return any(bool(r) for r in results)


def register_replication(server, db) -> None:
    """Mount /replicas/{collection}/{shard}/{op} (reference: clusterapi
    serve.go routes /replicas/indices/ to the replica store)."""

    def handler(subpath: str, payload: dict):
        parts = subpath.split("/")
        if len(parts) != 3:
            raise KeyError(subpath)
        collection_name, shard_name, op = parts
        col = db.get_collection(collection_name)
        if db.local_node not in col.sharding.nodes_for(shard_name):
            raise ValueError(
                f"node {db.local_node} is not a replica of {shard_name!r}")
        shard = col._load_shard(shard_name)

        if op == "prepare":
            if payload["kind"] == "put":
                objs = [StorageObject.from_bytes(raw)
                        for raw in payload["objects"]]
                shard.stage(payload["request_id"], ("put", objs))
            else:
                shard.stage(payload["request_id"],
                            ("delete", payload["uuid"],
                             payload["tombstone_ms"]))
            return {"ok": True}
        if op == "commit":
            return {"result": shard.commit_staged(payload["request_id"])}
        if op == "abort":
            shard.abort_staged(payload["request_id"])
            return {"ok": True}
        if op == "staged:status":
            # chaos-checker probe: an orphaned prepare must neither leak
            # (staged > 0 past the TTL) nor commit (expired_total is the
            # proof the TTL path fired)
            return shard.staged_status()
        if op == "digest":
            d = shard.object_digest(payload["uuid"])
            return {"digest": d}
        if op == "digests:bucket":
            return {"digests": shard.bucket_digests(payload["depth"],
                                                    payload["buckets"])}
        if op == "hashtree:level":
            tree, token = _tree_for_walk(shard, payload["depth"],
                                         payload.get("token"))
            return {"hashes": tree.level_hashes(payload["level"],
                                                payload["positions"]),
                    "token": token}
        if op == "sync:apply":
            n = shard.apply_sync(payload.get("objects", []),
                                 payload.get("deletes", []))
            return {"applied": n}
        if op == "objects:fetch":
            return {"objects": [shard.objects.get(u.encode())
                                for u in payload["uuids"]]}
        raise KeyError(op)

    server.route("/replicas/", handler)


# hashtree walks issue several level RPCs per beat; rebuilding the tree
# for each would turn O(diff*depth) exchanges into O(n*depth) hashing.
# Each walk gets a TOKEN naming its snapshot so concurrent walks from
# different peers never see a tree swapped mid-walk; a few snapshots are
# kept per shard (on the shard itself, so they die with it).
_tree_lock = threading.Lock()
_MAX_WALKS = 4


def _tree_for_walk(shard, depth: int, token: str | None):
    from collections import OrderedDict

    with _tree_lock:
        walks = getattr(shard, "_hashtree_walks", None)
        if walks is None:
            walks = shard._hashtree_walks = OrderedDict()
        if token is not None:
            cached = walks.get(token)
            if cached is not None and cached[0] == depth:
                return cached[1], token
            # token evicted/unknown: fall through to a fresh snapshot —
            # the walk continues on newer data, worst case a wasted round
        token = str(uuid_mod.uuid4())
        walks[token] = (depth, shard.build_hashtree(depth))
        while len(walks) > _MAX_WALKS:
            walks.popitem(last=False)
        return walks[token][1], token
