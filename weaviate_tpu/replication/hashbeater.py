"""Anti-entropy: background Merkle diff + object propagation.

Reference: adapters/repos/db/shard_hashbeater.go:32,216 — each shard
periodically compares its hashtree with every peer replica
(CollectShardDifferences), fetches digests for the differing ranges,
and propagates whichever side is newer. Runs on the cycle manager.

Convergence is OBSERVABLE (the clusterchaos tentpole): every round
feeds the module-level :data:`replication_status` registry —
per-shard last-beat age, rounds, entries reconciled, last diff size and
a divergence estimate — which `GET /v1/debug/replication` serves and
the ``weaviate_tpu_hashbeat_rounds_total`` /
``weaviate_tpu_replica_divergent_entries`` metrics mirror, so "did the
replicas actually converge after that partition healed" is a question
with a queryable answer instead of a shrug.
"""

from __future__ import annotations

import logging
import threading
import time

from weaviate_tpu.cluster.transport import RpcError, rpc
from weaviate_tpu.replication.hashtree import MerkleTree, digest_rank

logger = logging.getLogger(__name__)


class ReplicationStatus:
    """Per-shard anti-entropy bookkeeping (process-wide singleton
    :data:`replication_status`). Beats and consistent reads report in;
    the debug endpoint and metrics read out. All methods are cheap and
    never raise into the caller's repair path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._shards: dict[tuple[str, str], dict] = {}

    def _rec(self, collection: str, shard: str) -> dict:
        """Caller holds ``_lock``."""
        return self._shards.setdefault((collection, shard), {
            "rounds": 0, "reconciled_total": 0, "last_beat_t": 0.0,
            "read_divergence_total": 0, "divergent_known": 0,
            "known_remaining": {}, "peers": {}})

    def record_round(self, collection: str, shard: str,
                     peer_stats: dict[str, dict]) -> None:
        """One completed beat round (one Merkle walk against every peer)
        for one locally-owned shard. ``peer_stats[peer]``:
        {"reconciled", "divergent" (None when the peer was unreachable),
        "diff_buckets", "error"}."""
        now = time.time()
        reconciled = sum(s.get("reconciled") or 0
                         for s in peer_stats.values())
        with self._lock:
            rec = self._rec(collection, shard)
            rec["rounds"] += 1
            rec["reconciled_total"] += reconciled
            rec["last_beat_t"] = now
            for peer, s in peer_stats.items():
                rec["peers"][peer] = dict(s, t=now)
                if s.get("remaining") is not None:
                    rec["known_remaining"][peer] = s["remaining"]
            # PER-PEER last-known merge: an unreachable peer keeps its
            # most recent known reading — unknown is not zero, and a
            # round where only the in-sync peer answered must not reset
            # the gauge to 0 while the partitioned peer's divergence
            # grows behind the cut
            rec["divergent_known"] = sum(rec["known_remaining"].values())
            divergent_known = rec["divergent_known"]
        try:
            from weaviate_tpu.runtime.metrics import (
                hashbeat_rounds, replica_divergent_entries)

            hashbeat_rounds.labels(collection, shard).inc()
            # the gauge reports what the rounds LEFT divergent (observed
            # minus repaired, per-peer last-known): 0 once the replicas
            # converged; an unreachable peer contributes its most recent
            # known reading rather than a misleading 0 (the endpoint's
            # state field says "degraded" for the same round, and its
            # divergentEntries mirrors this exact value).
            replica_divergent_entries.labels(collection, shard).set(
                divergent_known)
        except Exception:  # pragma: no cover — registry unavailable
            pass

    def record_read_divergence(self, collection: str, shard: str,
                               stale: int) -> None:
        """A consistency-level read (finder) caught replicas disagreeing
        between beats — the read-path divergence signal."""
        if stale <= 0:
            return
        with self._lock:
            rec = self._rec(collection, shard)
            rec["read_divergence_total"] += stale

    @staticmethod
    def _state(rec: dict) -> str:
        if rec["rounds"] == 0:
            return "unknown"
        peers = rec["peers"].values()
        if any(s.get("error") for s in peers):
            return "degraded"  # at least one peer unreachable last round
        if all((s.get("remaining") or 0) == 0 for s in peers):
            return "converged"
        return "diverging"

    def snapshot(self) -> dict:
        now = time.time()
        shards = []
        with self._lock:
            items = sorted(self._shards.items())
            for (col, shard), rec in items:
                shards.append({
                    "collection": col,
                    "shard": shard,
                    "rounds": rec["rounds"],
                    "reconciledTotal": rec["reconciled_total"],
                    "lastBeatAgeSeconds": (
                        round(now - rec["last_beat_t"], 3)
                        if rec["last_beat_t"] else None),
                    # last KNOWN remaining divergence — all-unreachable
                    # rounds do not reset this to a misleading 0 (the
                    # state field reads "degraded" then); mirrors the
                    # weaviate_tpu_replica_divergent_entries gauge
                    "divergentEntries": rec["divergent_known"],
                    "lastDiffBuckets": sum(
                        s.get("diff_buckets") or 0
                        for s in rec["peers"].values()),
                    "readDivergenceTotal": rec["read_divergence_total"],
                    "state": self._state(rec),
                    "peers": {p: {k: v for k, v in s.items() if k != "t"}
                              for p, s in rec["peers"].items()},
                })
        return {
            "shards": shards,
            "totals": {
                "rounds": sum(s["rounds"] for s in shards),
                "reconciled": sum(s["reconciledTotal"] for s in shards),
                "converged": all(s["state"] == "converged"
                                 for s in shards) if shards else None,
            },
        }

    def reset(self) -> None:
        """Test hook (autouse fixture): metrics series are dropped too
        so a prior test's divergence gauge can't leak into the next."""
        with self._lock:
            keys = list(self._shards)
            self._shards.clear()
        try:
            from weaviate_tpu.runtime.metrics import (
                replica_divergent_entries)

            for col, shard in keys:
                replica_divergent_entries.remove(col, shard)
        except Exception:  # pragma: no cover
            pass


replication_status = ReplicationStatus()


class HashBeater:
    def __init__(self, collection, depth: int = 8):
        self.col = collection
        self.depth = depth

    def _peer_rpc(self, node: str, shard_name: str, op: str, payload: dict):
        # per-attempt ceiling = the shared remote-client config
        # (REMOTE_RPC_TIMEOUT_S, no longer a hard-coded 30s); rpc()
        # additionally caps it by any ambient deadline budget
        remote = self.col._require_remote(shard_name)
        return rpc(remote.resolver(node),
                   f"/replicas/{self.col.config.name}/{shard_name}/{op}",
                   payload, timeout=remote.timeout)

    def beat_shard(self, shard_name: str) -> int:
        """One anti-entropy round for one locally-owned shard against all
        peer replicas. Returns number of entries reconciled."""
        shard = self.col._load_shard(shard_name)
        peers = [n for n in self.col.sharding.nodes_for(shard_name)
                 if n != self.col.local_node]
        if not peers:
            return 0
        total = 0
        tree = shard.build_hashtree(self.depth)
        peer_stats: dict[str, dict] = {}
        for peer in peers:
            try:
                n, stats = self._beat_peer(shard, tree, shard_name, peer)
                total += n
                peer_stats[peer] = {"reconciled": n, "error": None, **stats}
            except (RpcError, KeyError) as e:
                # an unreachable peer leaves its divergence UNKNOWN, not
                # zero — the status registry reports the round degraded
                peer_stats[peer] = {"reconciled": 0, "divergent": None,
                                    "remaining": None,
                                    "diff_buckets": None, "error": str(e)}
                logger.debug("hashbeat %s/%s vs %s skipped: %s",
                             self.col.config.name, shard_name, peer, e)
        replication_status.record_round(self.col.config.name, shard_name,
                                        peer_stats)
        return total

    def _beat_peer(self, shard, tree: MerkleTree, shard_name: str,
                   peer: str) -> tuple[int, dict]:
        walk: dict = {}  # token pins the peer's snapshot across levels

        def peer_level(level: int, positions: list[int]):
            reply = self._peer_rpc(peer, shard_name, "hashtree:level",
                                   {"depth": self.depth, "level": level,
                                    "positions": positions,
                                    "token": walk.get("token")})
            walk["token"] = reply.get("token")
            return reply["hashes"]

        buckets = tree.diff_buckets(peer_level)
        if not buckets:
            return 0, {"divergent": 0, "remaining": 0, "diff_buckets": 0}
        theirs = {d["uuid"]: d for d in
                  self._peer_rpc(peer, shard_name, "digests:bucket",
                                 {"depth": self.depth,
                                  "buckets": buckets})["digests"]}
        mine = {d["uuid"]: d for d in shard.bucket_digests(self.depth, buckets)}

        push_objs: list[str] = []
        push_dels: list[dict] = []
        pull_uuids: list[str] = []
        pull_dels: list[dict] = []
        for uuid in set(mine) | set(theirs):
            m, t = mine.get(uuid), theirs.get(uuid)
            if t is None or (m is not None and digest_rank(m) > digest_rank(t)):
                if m["deleted"]:
                    push_dels.append({"uuid": uuid, "mtime": m["mtime"]})
                else:
                    push_objs.append(uuid)
            elif m is None or digest_rank(t) > digest_rank(m):
                if t["deleted"]:
                    pull_dels.append({"uuid": uuid, "mtime": t["mtime"]})
                else:
                    pull_uuids.append(uuid)

        divergent = (len(push_objs) + len(push_dels)
                     + len(pull_uuids) + len(pull_dels))
        n = 0
        if push_objs or push_dels:
            raws = [shard.objects.get(u.encode()) for u in push_objs]
            n += self._peer_rpc(peer, shard_name, "sync:apply",
                                {"objects": [r for r in raws if r],
                                 "deletes": push_dels})["applied"]
        if pull_uuids or pull_dels:
            raws = self._peer_rpc(peer, shard_name, "objects:fetch",
                                  {"uuids": pull_uuids})["objects"] \
                if pull_uuids else []
            applied = shard.apply_sync([r for r in raws if r], pull_dels)
            if applied:
                from weaviate_tpu.runtime.metrics import (
                    hashbeat_repairs_total)

                hashbeat_repairs_total.labels("pulled").inc(applied)
            n += applied
        if n:
            logger.info("hashbeat %s/%s vs %s reconciled %d entries",
                        self.col.config.name, shard_name, peer, n)
        # remaining = entries the walk saw diverged that this round did
        # NOT repair (rank ties both sides refuse, marker-skipped
        # pushes, fetch misses) — the convergence gauge reads this
        return n, {"divergent": divergent,
                   "remaining": max(0, divergent - n),
                   "diff_buckets": len(buckets)}

    def beat(self) -> bool:
        """Cycle callback: beat every locally-owned shard of the
        collection. True when anything was reconciled."""
        if self.col.config.replication.factor < 2:
            return False
        did = 0
        for name in list(self.col.sharding.shard_names):
            if self.col._is_local(name):
                try:
                    did += self.beat_shard(name)
                except Exception:
                    logger.exception("hashbeat failed for %s", name)
        return did > 0

    def roots_equal(self, shard_name: str) -> bool:
        """Do all replicas of ``shard_name`` report the same hashtree
        root right now? The convergence predicate the chaos checker and
        the antientropy bench poll between beat rounds."""
        shard = self.col._load_shard(shard_name)
        root = shard.build_hashtree(self.depth).root
        for peer in self.col.sharding.nodes_for(shard_name):
            if peer == self.col.local_node:
                continue
            reply = self._peer_rpc(peer, shard_name, "hashtree:level",
                                   {"depth": self.depth, "level": 0,
                                    "positions": [0], "token": None})
            if reply["hashes"][0] != root:
                return False
        return True
