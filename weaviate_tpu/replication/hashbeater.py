"""Anti-entropy: background Merkle diff + object propagation.

Reference: adapters/repos/db/shard_hashbeater.go:32,216 — each shard
periodically compares its hashtree with every peer replica
(CollectShardDifferences), fetches digests for the differing ranges,
and propagates whichever side is newer. Runs on the cycle manager.
"""

from __future__ import annotations

import logging

from weaviate_tpu.cluster.transport import RpcError, rpc
from weaviate_tpu.replication.hashtree import MerkleTree, digest_rank

logger = logging.getLogger(__name__)


class HashBeater:
    def __init__(self, collection, depth: int = 8):
        self.col = collection
        self.depth = depth

    def _peer_rpc(self, node: str, shard_name: str, op: str, payload: dict):
        # per-attempt ceiling = the shared remote-client config
        # (REMOTE_RPC_TIMEOUT_S, no longer a hard-coded 30s); rpc()
        # additionally caps it by any ambient deadline budget
        remote = self.col._require_remote(shard_name)
        return rpc(remote.resolver(node),
                   f"/replicas/{self.col.config.name}/{shard_name}/{op}",
                   payload, timeout=remote.timeout)

    def beat_shard(self, shard_name: str) -> int:
        """One anti-entropy round for one locally-owned shard against all
        peer replicas. Returns number of entries reconciled."""
        shard = self.col._load_shard(shard_name)
        peers = [n for n in self.col.sharding.nodes_for(shard_name)
                 if n != self.col.local_node]
        if not peers:
            return 0
        total = 0
        tree = shard.build_hashtree(self.depth)
        for peer in peers:
            try:
                total += self._beat_peer(shard, tree, shard_name, peer)
            except (RpcError, KeyError) as e:
                logger.debug("hashbeat %s/%s vs %s skipped: %s",
                             self.col.config.name, shard_name, peer, e)
        return total

    def _beat_peer(self, shard, tree: MerkleTree, shard_name: str,
                   peer: str) -> int:
        walk: dict = {}  # token pins the peer's snapshot across levels

        def peer_level(level: int, positions: list[int]):
            reply = self._peer_rpc(peer, shard_name, "hashtree:level",
                                   {"depth": self.depth, "level": level,
                                    "positions": positions,
                                    "token": walk.get("token")})
            walk["token"] = reply.get("token")
            return reply["hashes"]

        buckets = tree.diff_buckets(peer_level)
        if not buckets:
            return 0
        theirs = {d["uuid"]: d for d in
                  self._peer_rpc(peer, shard_name, "digests:bucket",
                                 {"depth": self.depth,
                                  "buckets": buckets})["digests"]}
        mine = {d["uuid"]: d for d in shard.bucket_digests(self.depth, buckets)}

        push_objs: list[str] = []
        push_dels: list[dict] = []
        pull_uuids: list[str] = []
        pull_dels: list[dict] = []
        for uuid in set(mine) | set(theirs):
            m, t = mine.get(uuid), theirs.get(uuid)
            if t is None or (m is not None and digest_rank(m) > digest_rank(t)):
                if m["deleted"]:
                    push_dels.append({"uuid": uuid, "mtime": m["mtime"]})
                else:
                    push_objs.append(uuid)
            elif m is None or digest_rank(t) > digest_rank(m):
                if t["deleted"]:
                    pull_dels.append({"uuid": uuid, "mtime": t["mtime"]})
                else:
                    pull_uuids.append(uuid)

        n = 0
        if push_objs or push_dels:
            raws = [shard.objects.get(u.encode()) for u in push_objs]
            n += self._peer_rpc(peer, shard_name, "sync:apply",
                                {"objects": [r for r in raws if r],
                                 "deletes": push_dels})["applied"]
        if pull_uuids or pull_dels:
            raws = self._peer_rpc(peer, shard_name, "objects:fetch",
                                  {"uuids": pull_uuids})["objects"] \
                if pull_uuids else []
            applied = shard.apply_sync([r for r in raws if r], pull_dels)
            if applied:
                from weaviate_tpu.runtime.metrics import (
                    hashbeat_repairs_total)

                hashbeat_repairs_total.labels("pulled").inc(applied)
            n += applied
        if n:
            logger.info("hashbeat %s/%s vs %s reconciled %d entries",
                        self.col.config.name, shard_name, peer, n)
        return n

    def beat(self) -> bool:
        """Cycle callback: beat every locally-owned shard of the
        collection. True when anything was reconciled."""
        if self.col.config.replication.factor < 2:
            return False
        did = 0
        for name in list(self.col.sharding.shard_names):
            if self.col._is_local(name):
                try:
                    did += self.beat_shard(name)
                except Exception:
                    logger.exception("hashbeat failed for %s", name)
        return did > 0
