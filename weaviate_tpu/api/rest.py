"""REST API: the /v1 surface.

Reference: adapters/handlers/rest/ (go-swagger server; spec
openapi-specs/schema.json) — /v1/objects, /v1/schema (+tenants),
/v1/batch/objects, /v1/graphql, /v1/nodes, /v1/meta, /.well-known/*.
Hand-rolled stdlib server instead of generated swagger code; the route
set and JSON shapes mirror the reference handlers
(handlers_objects.go, handlers_schema.go, handlers_batch_objects.go).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from weaviate_tpu import __version__ as VERSION

# Weaviate API level implemented (reference openapi-specs/schema.json)
API_VERSION = "1.25.2"
from weaviate_tpu.cluster.transport import CircuitOpenError
from weaviate_tpu.db.shard import ShardReadOnlyError
from weaviate_tpu.filters.filters import Filter
from weaviate_tpu.runtime import (degrade, faultline, retry, tailboard,
                                  tracing)
from weaviate_tpu.runtime.memwatch import InsufficientMemoryError
from weaviate_tpu.schema.config import CollectionConfig, Property

logger = logging.getLogger(__name__)


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class RawResponse:
    """Non-JSON dispatch result (e.g. Prometheus text exposition)."""

    __slots__ = ("body", "content_type")

    def __init__(self, body: bytes, content_type: str):
        self.body = body
        self.content_type = content_type


# the fixed REST route classes — root-span names (which become
# span_duration label values) must come from this closed set, never from
# raw client paths, or a URL scanner inflates the metrics registry
# without bound
_ROUTE_CLASSES = frozenset((
    ".well-known", "meta", "metrics", "nodes", "cluster",
    "tenant-activity", "graphql", "schema", "objects", "batch",
    "backups", "classifications", "debug"))
# probe/scrape/introspection routes: health checks and metrics scrapes
# arrive every few seconds in production and would evict real query
# traces from the debug ring — they are not traced unless forced
_UNTRACED_ROUTES = frozenset(
    (".well-known", "meta", "metrics", "nodes", "debug", "unmatched"))


# the debug surface, declaratively: this table drives BOTH dispatch and
# the GET /v1/debug index, so an endpoint cannot exist without being
# listed (tests assert the round trip). Keys are the /v1/debug/<name>
# path segment.
DEBUG_ENDPOINTS = {
    "traces": "Finished-trace ring (newest first; ?limit=N). "
              "?tail=true serves the tail-retained ring instead: slow, "
              "errored, deadline-exceeded, degraded and fault-injected "
              "requests kept at completion regardless of "
              "TRACE_SAMPLE_RATE, with per-phase timings.",
    "memory": "HBM ledger breakdown: per-collection/shard/component "
              "device bytes, allocator-vs-ledger delta, admission "
              "watermarks and pressure state.",
    "storage": "Per-bucket crash-recovery reports from the last open: "
               "WAL frames replayed, torn tails truncated, files "
               "quarantined .corrupt, segments rebuilt.",
    "replication": "Anti-entropy convergence: hashbeat rounds, "
                   "divergent-entry estimates, staged-2PC state and "
                   "breaker/peer health per replicated shard.",
    "perf": "Last benchkeeper perf-gate verdict: per-entry values, "
            "deltas vs the reasoned baseline, regressions/stale/"
            "missing counts.",
    "slo": "SLO engine state: per-objective availability/latency "
           "windows, good/bad counts, multi-window burn rates, and "
           "which objectives are currently burning.",
    "flight": "Flight recorder: recent batcher and native-plane "
              "dispatch records (batch size, k bucket, queue depth, "
              "wait, epoch fanout, attributed device ms + source, "
              "transfer-window occupancy), the structured slow-query "
              "log, and on-disk incident snapshots.",
    "kernelscope": "Device-time truth plane: per-(kind, batch, k) "
                   "compiled-variant residency EWMAs with their "
                   "drain/wall attribution source, the sampled memcpy "
                   "estimator, per-tenant device-seconds meters and "
                   "dispatch totals. Per-query plans ride "
                   "?explain=true on /v1/graphql (or x-explain gRPC "
                   "metadata).",
    "profile": "On-demand kernel profiles: paramless lists the last K "
               "persisted captures; ?ms=N runs a jax.profiler capture "
               "for N ms and returns per-kernel device-ms ranked by "
               "the kernel registry (?id=<capture> fetches a full "
               "persisted capture).",
    "drift": "Driftwatch verdict plane: open findings with the gate "
             "verdict, per-entry trend deltas from the last live "
             "telemetry classification against benchkeeper bands, and "
             "per-canary state (probe set, sealed references, recall/"
             "residency history through the real query batcher).",
}


def _route_class(path: str) -> str:
    segs = [s for s in path.split("/") if s]
    if segs and segs[0] == "v1":
        segs = segs[1:]
    head = segs[0] if segs else ".well-known"
    return head if head in _ROUTE_CLASSES else "unmatched"


def object_to_json(class_name: str, obj, tenant: str | None = None) -> dict:
    out = {
        "class": class_name,
        "id": obj.uuid,
        "properties": obj.properties,
        "creationTimeUnix": obj.creation_time_ms,
        "lastUpdateTimeUnix": obj.last_update_time_ms,
    }
    if tenant:
        out["tenant"] = tenant
    if obj.vector is not None:
        out["vector"] = np.asarray(obj.vector).tolist()
    named = {k: np.asarray(v).tolist() for k, v in obj.vectors.items() if k}
    if named:
        out["vectors"] = named
    return out


def property_from_json(d: dict) -> Property:
    """Accepts native {"name", "data_type"} and reference-style
    {"name", "dataType": ["text"]} payloads."""
    data_type = d.get("data_type")
    if data_type is None and d.get("dataType"):
        dt = d["dataType"]
        data_type = dt[0] if isinstance(dt, list) else dt
    return Property(
        name=d["name"],
        data_type=data_type or "text",
        tokenization=d.get("tokenization", "word"),
        index_filterable=d.get("index_filterable",
                               d.get("indexFilterable", True)),
        index_searchable=d.get("index_searchable",
                               d.get("indexSearchable", True)),
        description=d.get("description", ""),
    )


def _index_config_from_json(index_type: str | None, d: dict | None):
    """Map the reference's vectorIndexConfig JSON (entities/vectorindex/
    {hnsw,flat}/config.go) onto VectorIndexConfig; native snake_case keys
    pass straight through."""
    from weaviate_tpu.schema.config import VectorIndexConfig
    import dataclasses

    out = VectorIndexConfig()
    if index_type:
        out.index_type = index_type
    if not d:
        return out
    native = {f.name for f in dataclasses.fields(VectorIndexConfig)}
    for k, v in d.items():
        if k in native:
            setattr(out, k, v)
    if "distance" in d:
        out.metric = d["distance"]
    if "efConstruction" in d:
        out.ef_construction = d["efConstruction"]
    if "maxConnections" in d:
        out.max_connections = d["maxConnections"]
    pq = d.get("pq") or {}
    if pq.get("enabled"):
        out.quantization = "pq"
        out.pq_segments = pq.get("segments") or None
        out.pq_centroids = pq.get("centroids", out.pq_centroids)
    bq = d.get("bq") or {}
    if bq.get("enabled"):
        out.quantization = "bq"
        out.rescore_limit = bq.get("rescoreLimit", out.rescore_limit)
    return out


def class_to_wire(cfg: CollectionConfig) -> dict:
    """Serialize a collection config as the reference's models.Class JSON
    (openapi-specs/schema.json "Class") — the shape the official client's
    _CollectionConfig parser and every external weaviate tool expect.
    The internal snake_case dict (``cfg.to_dict()``) stays for
    persistence and the intra-cluster API; the PUBLIC wire speaks
    camelCase."""
    def _prop(p) -> dict:
        out = {
            "name": p.name,
            "dataType": [p.data_type],
            "description": p.description,
            "indexFilterable": p.index_filterable,
            "indexSearchable": p.index_searchable,
            "tokenization": p.tokenization,
        }
        if p.nested:
            out["nestedProperties"] = [_prop(np_) for np_ in p.nested]
        return out

    def _index_cfg(ix) -> dict:
        out = {
            "distance": ix.metric,
            "ef": ix.ef,
            "efConstruction": ix.ef_construction,
            "maxConnections": ix.max_connections,
            "pq": {"enabled": ix.quantization == "pq",
                   "segments": ix.pq_segments or 0,
                   "centroids": ix.pq_centroids},
            "bq": {"enabled": ix.quantization == "bq",
                   "rescoreLimit": ix.rescore_limit},
        }
        if ix.index_type == "dynamic":
            out["threshold"] = ix.flat_to_ann_threshold
        return out

    inv = cfg.inverted
    default = None
    named = {}
    for v in cfg.vectors:
        if v.name == "":
            default = v
        else:
            named[v.name] = v
    if default is None and not named:
        from weaviate_tpu.schema.config import VectorConfig

        default = VectorConfig()
    out = {
        "class": cfg.name,
        "description": cfg.description,
        "properties": [_prop(p) for p in cfg.properties],
        "invertedIndexConfig": {
            "bm25": {"k1": inv.bm25_k1, "b": inv.bm25_b},
            "stopwords": {"preset": inv.stopwords_preset,
                          "additions": inv.stopwords_additions,
                          "removals": inv.stopwords_removals},
            "indexTimestamps": inv.index_timestamps,
            "indexNullState": inv.index_null_state,
            "indexPropertyLength": inv.index_property_length,
            "cleanupIntervalSeconds": 60,
        },
        "multiTenancyConfig": {
            "enabled": cfg.multi_tenancy.enabled,
            "autoTenantCreation": cfg.multi_tenancy.auto_tenant_creation,
            "autoTenantActivation": cfg.multi_tenancy.auto_tenant_activation,
        },
        "replicationConfig": {
            "factor": cfg.replication.factor,
            "asyncEnabled": cfg.replication.async_enabled,
        },
        "shardingConfig": {
            "desiredCount": cfg.sharding.desired_count,
            "virtualPerPhysical": cfg.sharding.virtual_per_physical,
        },
        "moduleConfig": cfg.module_config,
    }
    if default is not None:
        out["vectorizer"] = default.vectorizer
        out["vectorIndexType"] = default.index.index_type
        out["vectorIndexConfig"] = _index_cfg(default.index)
    if named:
        out["vectorConfig"] = {
            name: {
                "vectorizer": {v.vectorizer: v.module_config or {}},
                "vectorIndexType": v.index.index_type,
                "vectorIndexConfig": _index_cfg(v.index),
            } for name, v in named.items()
        }
    return out


def config_from_json(d: dict) -> CollectionConfig:
    """Accepts the native config dict AND the reference's class JSON shape
    (entities/models.Class): top-level "class"/"vectorizer"/
    "vectorIndexType"/"vectorIndexConfig"/"moduleConfig", camelCase
    sub-configs, and named-vector "vectorConfig"."""
    from weaviate_tpu.schema.config import (
        InvertedIndexConfig,
        MultiTenancyConfig,
        ReplicationConfig,
        ShardingConfig,
        VectorConfig,
    )

    d = dict(d)
    if "name" not in d and "class" in d:
        d["name"] = d.pop("class")
    if d.get("properties") and isinstance(d["properties"][0], dict):
        # normalize per property — payloads may mix native and
        # reference-style entries
        d["properties"] = [vars(property_from_json(p)) if isinstance(p, dict)
                           else p for p in d["properties"]]

    # reference-style top-level vectorizer / index config → default space
    vectorizer = d.pop("vectorizer", None)
    v_index_type = d.pop("vectorIndexType", None)
    v_index_cfg = d.pop("vectorIndexConfig", None)
    module_config = d.pop("moduleConfig", None)
    named = d.pop("vectorConfig", None)  # weaviate named vectors
    if "vectors" not in d and (vectorizer or v_index_type or v_index_cfg
                               or named):
        vecs = []
        if named:
            for vname, vc in named.items():
                vz, mc = "none", {}
                raw_vz = vc.get("vectorizer")
                if isinstance(raw_vz, dict) and raw_vz:
                    vz = next(iter(raw_vz))
                    mc = raw_vz[vz] or {}
                elif isinstance(raw_vz, str):
                    vz = raw_vz
                vecs.append(VectorConfig(
                    name=vname,
                    index=_index_config_from_json(
                        vc.get("vectorIndexType"),
                        vc.get("vectorIndexConfig")),
                    vectorizer=vz if vz else "none",
                    module_config=mc,
                ))
        else:
            mc = {}
            if isinstance(module_config, dict) and vectorizer and \
                    vectorizer in module_config:
                mc = module_config[vectorizer] or {}
            vecs.append(VectorConfig(
                index=_index_config_from_json(v_index_type, v_index_cfg),
                vectorizer=vectorizer or "none",
                module_config=mc,
            ))
        d["vectors"] = [vars(v) if not isinstance(v, dict) else v
                        for v in vecs]
        d["vectors"] = [
            {**v, "index": vars(v["index"])
             if not isinstance(v["index"], dict) else v["index"]}
            for v in d["vectors"]
        ]
    if module_config is not None and "module_config" not in d:
        d["module_config"] = module_config

    # camelCase sub-config shims
    inv = d.pop("invertedIndexConfig", None)
    if inv is not None and "inverted" not in d:
        bm25 = inv.get("bm25") or {}
        sw = inv.get("stopwords") or {}
        d["inverted"] = vars(InvertedIndexConfig(
            bm25_k1=bm25.get("k1", 1.2),
            bm25_b=bm25.get("b", 0.75),
            stopwords_preset=sw.get("preset", "en"),
            stopwords_additions=sw.get("additions") or [],
            stopwords_removals=sw.get("removals") or [],
            index_timestamps=inv.get("indexTimestamps", False),
            index_null_state=inv.get("indexNullState", False),
            index_property_length=inv.get("indexPropertyLength", False),
        ))
    sh = d.pop("shardingConfig", None)
    if sh is not None and "sharding" not in d:
        d["sharding"] = vars(ShardingConfig(
            desired_count=sh.get("desiredCount", 1),
            virtual_per_physical=sh.get("virtualPerPhysical", 128),
        ))
    mt = d.pop("multiTenancyConfig", None)
    if mt is not None and "multi_tenancy" not in d:
        d["multi_tenancy"] = vars(MultiTenancyConfig(
            enabled=mt.get("enabled", False),
            auto_tenant_creation=mt.get("autoTenantCreation", False),
            auto_tenant_activation=mt.get("autoTenantActivation", False),
        ))
    rp = d.pop("replicationConfig", None)
    if rp is not None and "replication" not in d:
        d["replication"] = vars(ReplicationConfig(
            factor=rp.get("factor", 1),
            async_enabled=rp.get("asyncEnabled", False),
        ))

    # drop unknown top-level keys rather than TypeError-ing the constructor
    import dataclasses

    known = {f.name for f in dataclasses.fields(CollectionConfig)}
    d = {k: v for k, v in d.items() if k in known}
    return CollectionConfig.from_dict(d)


class RestServer:
    """``db``: the node-local Database. ``schema_target``: where schema
    writes go — the Database itself (single node) or a ClusterNode
    (Raft path); both expose the same method names. ``node``: optional
    ClusterNode for /v1/nodes."""

    _DEFAULT_GRAPHQL = object()  # sentinel: build an executor; None = off

    def __init__(self, db, host: str = "127.0.0.1", port: int = 0,
                 schema_target=None, node=None,
                 graphql_executor=_DEFAULT_GRAPHQL,
                 modules=None, auth=None,
                 query_deadline_s: float | None = None):
        self.db = db
        self.schema_target = schema_target or db
        self.node = node
        self.auth = auth  # AuthStack | None (None = open access)
        # default request time budget (0 = none unless the client sends
        # X-Request-Timeout / ?timeout=); propagated via retry.deadline
        if query_deadline_s is None:
            query_deadline_s = float(
                os.environ.get("QUERY_DEADLINE_S", "0") or 0)
        self.query_deadline_s = query_deadline_s
        if graphql_executor is RestServer._DEFAULT_GRAPHQL:
            from weaviate_tpu.api.graphql import GraphQLExecutor

            graphql_executor = GraphQLExecutor(db, modules)
        self.graphql_executor = graphql_executor
        self.modules = modules  # module Provider for import vectorization
        if modules is not None:
            from weaviate_tpu.backup import BackupManager

            self.backup_manager = BackupManager(
                db, modules,
                node_name=getattr(node, "name", None) or db.local_node,
                schema_target=self.schema_target, node=node)
        else:
            self.backup_manager = None
        self.classification_manager = None  # built lazily on first use
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _run(self, method: str):
                parsed = urllib.parse.urlparse(self.path)
                params = {k: v[0] for k, v in
                          urllib.parse.parse_qs(parsed.query).items()}
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b""
                # every data-path request gets a root trace (cheap spans
                # are always on); ?trace=true forces device-time
                # sampling. Probe/scrape routes skip tracing (unless
                # forced) so they can't flood the debug ring, and auth
                # runs BEFORE the trace opens — unauthenticated clients
                # must not be able to evict real traces from the ring.
                force = params.get("trace") == "true"
                route = _route_class(parsed.path)
                if route in _UNTRACED_ROUTES and not force:
                    trace_cm = contextlib.nullcontext()
                else:
                    trace_cm = tracing.trace(f"rest.{method} /{route}",
                                             force=force)
                # request time budget: explicit header/param wins, else
                # the server default; 0/absent = no deadline. The budget
                # propagates down through the batcher, shard fan-out and
                # every transport call (retry.remaining caps per-attempt
                # timeouts), so a retry can never outlive the request.
                budget = outer.query_deadline_s
                try:
                    raw_budget = self.headers.get("X-Request-Timeout") \
                        or params.get("timeout")
                    if raw_budget:
                        budget = float(raw_budget)
                except ValueError:
                    budget = outer.query_deadline_s
                # content negotiation for /v1/metrics (OpenMetrics with
                # exemplars) rides params — dispatch has no header access
                accept = self.headers.get("Accept", "")
                if "application/openmetrics-text" in accept:
                    params["_accept_openmetrics"] = "true"
                extra_headers: dict[str, str] = {}
                markers: list = []
                # always-on timeline (tailboard): opened for the same
                # request set tracing covers; wraps the WHOLE handling
                # INCLUDING the error mapping below, so the tail-based
                # keep/drop decision sees the response status
                timeline_cm = (
                    contextlib.nullcontext()
                    if route in _UNTRACED_ROUTES and not force
                    else tailboard.request(route, method=method))
                def _handle():
                    nonlocal markers
                    try:
                        if outer.auth is not None and \
                                not parsed.path.startswith("/.well-known"):
                            from weaviate_tpu.auth import (
                                AuthError,
                                ForbiddenError,
                            )

                            # POST /v1/graphql is query-only (this API
                            # has no mutations) — same verb as gRPC
                            # Search
                            verb = "read" if method in ("GET", "HEAD") \
                                or parsed.path == "/v1/graphql" else "write"
                            try:
                                outer.auth.check(
                                    self.headers.get("Authorization"),
                                    verb)
                            except AuthError as e:
                                raise ApiError(401, str(e))
                            except ForbiddenError as e:
                                raise ApiError(403, str(e))
                        with trace_cm, retry.deadline(budget), \
                                degrade.collecting(), \
                                faultline.node_scope(outer.db.local_node):
                            body = json.loads(raw) if raw else None
                            status, payload = outer.dispatch(
                                method, parsed.path, params, body)
                            # explicit partial-result marker: a degraded
                            # scatter-gather or downgraded-consistency
                            # read must be visible to the client, never
                            # silent
                            markers = degrade.snapshot()
                            if markers and isinstance(payload, dict):
                                payload["degraded"] = markers
                        return status, payload
                    except ApiError as e:
                        return e.status, {"error": [{"message": e.message}]}
                    except (KeyError, FileNotFoundError) as e:
                        return 404, {"error": [{"message": str(e)}]}
                    except ValueError as e:
                        return 422, {"error": [{"message": str(e)}]}
                    except ShardReadOnlyError as e:
                        return 422, {"error": [{"message": str(e)}]}
                    except InsufficientMemoryError as e:
                        # typed 507 Insufficient Storage: admission
                        # control refused BEFORE allocating (memwatch
                        # watermarks) — the client should back off or
                        # free capacity, not retry blindly
                        return 507, {"error": [{
                            "message": str(e),
                            "code": "INSUFFICIENT_MEMORY",
                            "projectedBytes": e.projected,
                            "budgetBytes": e.budget,
                            "usageSource": e.source,
                        }]}
                    except retry.DeadlineExceeded as e:
                        # typed 504: the request's time budget ran out —
                        # not a generic 500, so clients/gateways can
                        # distinguish "took too long" from "broke"
                        return 504, {"error": [{
                            "message": str(e),
                            "code": "DEADLINE_EXCEEDED",
                            "layer": e.layer,
                        }]}
                    except retry.OverloadedError as e:
                        # RFC 9110: integer delta-seconds (fractions
                        # would be ignored by conforming clients),
                        # floor of 1
                        extra_headers["Retry-After"] = \
                            str(max(1,
                                    -(-int(e.retry_after_s * 1000) // 1000)))
                        return 503, {"error": [{
                            "message": str(e),
                            "code": "OVERLOADED",
                        }]}
                    except CircuitOpenError as e:
                        # the whole request depended on a peer whose
                        # breaker is open (e.g. an unreplicated remote
                        # shard write): retriable 503 with the breaker's
                        # cooldown hint (integer delta-seconds per
                        # RFC 9110, floor of 1)
                        extra_headers["Retry-After"] = \
                            str(max(1,
                                    -(-int(e.retry_after_s * 1000) // 1000)))
                        return 503, {"error": [{
                            "message": str(e),
                            "code": "CIRCUIT_OPEN",
                        }]}
                    except Exception as e:
                        logger.exception("REST %s %s failed", method,
                                         self.path)
                        return 500, {"error": [{"message": str(e)}]}

                with timeline_cm:
                    # the error mapping runs INSIDE the timeline (and the
                    # trace closes inside _handle), so the tail keep/drop
                    # decision sees both the finished trace AND the
                    # response status
                    status, payload = _handle()
                    tailboard.complete(status, degraded=bool(markers))
                if isinstance(payload, RawResponse):
                    self.send_response(status)
                    self.send_header("Content-Type", payload.content_type)
                    self.send_header("Content-Length",
                                     str(len(payload.body)))
                    self.end_headers()
                    if method != "HEAD":
                        self.wfile.write(payload.body)
                    return
                data = b"" if payload is None else json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for hk, hv in extra_headers.items():
                    self.send_header(hk, hv)
                self.end_headers()
                if method != "HEAD":
                    self.wfile.write(data)

            def do_GET(self):
                self._run("GET")

            def do_POST(self):
                self._run("POST")

            def do_PUT(self):
                self._run("PUT")

            def do_PATCH(self):
                self._run("PATCH")

            def do_DELETE(self):
                self._run("DELETE")

            def do_HEAD(self):
                self._run("HEAD")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._httpd.serve_forever,
                                            daemon=True,
                                            name=f"rest-{self.port}")
            self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread = None

    # -- routing --------------------------------------------------------------

    def dispatch(self, method: str, path: str, params: dict, body):
        seg = [s for s in path.split("/") if s]
        # /.well-known/* — the reference serves these under the /v1
        # basePath (swagger basePath /v1; the official client probes
        # /v1/.well-known/...), and bare-root works too; accept both.
        if seg[:2] == ["v1", ".well-known"]:
            seg = seg[1:]
        if seg[:1] == [".well-known"]:
            if seg[1:] == ["ready"] or seg[1:] == ["live"]:
                return 200, {}
            if seg[1:] == ["openid-configuration"]:
                oidc = None if self.auth is None else \
                    self.auth.openid_configuration()
                if oidc is None:
                    raise ApiError(404, "OIDC is not configured")
                return 200, oidc
            raise KeyError(path)
        if not seg or seg[0] != "v1":
            raise KeyError(path)
        seg = seg[1:]

        if seg == ["meta"]:
            # `version` carries the WEAVIATE API level this server speaks
            # (the reference pins 1.25.2, openapi-specs/schema.json) — the
            # official v4 client parses it as semver and refuses anything
            # below 1.23.7. The implementation's own version rides in a
            # separate field.
            return 200, {"version": API_VERSION, "hostname": self.address,
                         "tpuServerVersion": VERSION,
                         "grpcMaxMessageSize": 104858000,
                         "modules": self.modules.meta()
                         if self.modules is not None else {}}
        if seg == ["metrics"]:
            # real Prometheus exposition (the reference serves text on
            # the monitoring port; serving it here too lets Prometheus
            # scrape either port). A JSON wrapper would not parse.
            # OpenMetrics negotiation (Accept header, or ?format=) gets
            # exemplar-carrying buckets + the # EOF terminator; the
            # shared scrape() helper runs the read-point refreshes
            from weaviate_tpu.runtime.metrics import scrape

            om = (params.get("_accept_openmetrics") == "true"
                  or params.get("format") == "openmetrics")
            return 200, RawResponse(*scrape(openmetrics=om))
        if seg[:1] == ["debug"]:
            return self._debug(seg[1:], params)
        if seg == ["nodes"]:
            verbose = params.get("output") == "verbose"
            return 200, {"nodes": self._nodes_payload(verbose=verbose)}
        if seg == ["cluster", "statistics"]:
            # Raft/cluster introspection (reference: /v1/cluster/statistics,
            # handlers for cluster statistics over the raft Store)
            if self.node is None:
                return 200, {"statistics": [{
                    "name": self.db.local_node, "status": "HEALTHY",
                    "raft": None, "standalone": True}],
                    "synchronized": True}
            raft = self.node.raft
            return 200, {"statistics": [{
                "name": self.node.name,
                "status": "HEALTHY",
                "leaderId": raft.leader_id,
                "raft": {"state": raft.role, "term": raft.current_term,
                         "commitIndex": raft.commit_index,
                         "appliedIndex": raft.commit_index,
                         "numPeers": len(raft.peers) - 1},
                "open": True, "bootstrapped": True,
                "dbLoaded": True,
                "isVoter": True,
                "candidates": {n: True for n in raft.peers},
            }], "synchronized": raft.leader_id is not None}
        if seg == ["tenant-activity"]:
            # hot/cold tenant usage (reference:
            # rest/tenantactivity/handler.go)
            out = {}
            for name in self.db.list_collections():
                snap = self.db.get_collection(name).tenant_activity_snapshot()
                if snap:
                    out[name] = snap
            return 200, out
        if seg == ["graphql"] and method == "POST":
            if self.graphql_executor is None:
                raise ApiError(501, "graphql not enabled")
            if params.get("explain") == "true":
                # per-query EXPLAIN (kernelscope): install a request-
                # level sink on THIS thread; the batcher merges each
                # dispatch's plan back here after the waiter wakes.
                # Explain never perturbs the dispatch itself — same
                # program, padding and slicing as the unexplained path.
                from weaviate_tpu.runtime import kernelscope

                token = kernelscope.explain_begin()
                try:
                    out = self.graphql_executor(body or {})
                finally:
                    explain_plan = kernelscope.explain_end(token)
                if isinstance(out, dict):
                    out["_explain"] = explain_plan
            else:
                out = self.graphql_executor(body or {})
            if isinstance(out, dict) and params.get("trace") == "true" \
                    and tracing.is_sampled():
                # the inline breakdown rides ONLY explicitly requested
                # (?trace=true) responses — background TRACE_SAMPLE_RATE
                # sampling must not change response shapes clients see
                out["_debug"] = {
                    "traceId": tracing.current_trace_id(),
                    "timing": tracing.current_timing(),
                }
            return 200, out
        if seg[:1] == ["schema"]:
            return self._schema(method, seg[1:], body)
        if seg[:1] == ["objects"]:
            return self._objects(method, seg[1:], params, body)
        if seg == ["batch", "objects"] and method == "POST":
            return self._batch_objects(body or {})
        if seg == ["batch", "objects"] and method == "DELETE":
            return self._batch_delete(body or {}, params)
        if seg == ["batch", "references"] and method == "POST":
            return self._batch_references(body or [])
        if seg[:1] == ["backups"]:
            return self._backups(method, seg[1:], body)
        if seg[:1] == ["classifications"]:
            return self._classifications(method, seg[1:], body)
        raise KeyError(path)

    def _classifications(self, method: str, seg: list[str], body):
        """POST /v1/classifications, GET /v1/classifications/{id}
        (reference: handlers_classification.go)."""
        if method == "POST" and not seg:
            from weaviate_tpu.api.validation import (CLASSIFICATION,
                                                     validate_body)

            validate_body(CLASSIFICATION, body or {}, "classification")
        from weaviate_tpu.classification import (
            ClassificationError,
            ClassificationManager,
        )

        if self.classification_manager is None:
            self.classification_manager = ClassificationManager(
                self.db, self.modules)
        mgr = self.classification_manager
        try:
            if not seg and method == "POST":
                b = body or {}
                settings = b.get("settings") or {}
                where = b.get("filters", {}).get("sourceWhere") \
                    if b.get("filters") else None
                train = b.get("filters", {}).get("trainingSetWhere") \
                    if b.get("filters") else None
                from weaviate_tpu.filters.filters import Filter

                return 201, mgr.start(
                    b.get("class", ""),
                    b.get("classifyProperties") or [],
                    based_on_properties=b.get("basedOnProperties"),
                    kind=b.get("type", "knn"), settings=settings,
                    where=None if where is None else Filter.from_dict(where),
                    training_set_where=None if train is None
                    else Filter.from_dict(train),
                    tenant=b.get("tenant"))
            if len(seg) == 1 and method == "GET":
                return 200, mgr.get(seg[0])
        except ClassificationError as e:
            raise ApiError(422, str(e))
        raise KeyError("/v1/classifications/" + "/".join(seg))

    def _patch_merge(self, col, uuid: str, body: dict, tenant):
        """PATCH /v1/objects/{class}/{id} merge semantics (reference:
        usecases/objects/merge.go). Caller holds col.uuid_lock(uuid)."""
        existing = col.get_object(uuid, tenant=tenant)
        if existing is None:
            raise ApiError(404, f"object {uuid} not found")
        merged = dict(existing.properties)
        merged.update(body.get("properties", {}))
        body["properties"] = merged

        # Carry existing vectors forward for spaces with no vectorizer —
        # vectorizer-backed spaces are left absent so _put_object re-embeds
        # the merged properties (reference re-vectorizes on merge; a copied
        # vector would pin the pre-edit embedding forever). If this server
        # CANNOT re-embed (no module provider, or the module isn't
        # registered), keep the existing vector: stale beats silently
        # dropping the object from vector search.
        def _keeps(vec_name):
            vc = col.config.vector_config(vec_name)
            if vc is None or vc.vectorizer in ("", "none"):
                return True
            return (self.modules is None
                    or self.modules.get(vc.vectorizer) is None)

        if "vector" not in body and existing.vector is not None \
                and _keeps(""):
            body["vector"] = np.asarray(existing.vector).tolist()
        if "vectors" not in body:
            named = {k: np.asarray(v).tolist()
                     for k, v in existing.vectors.items()
                     if k and _keeps(k)}
            if named:
                body["vectors"] = named
        body["creationTimeUnix"] = existing.creation_time_ms
        return self._put_object(body, tenant)

    def _references(self, method: str, class_name: str, uuid: str,
                    prop: str, body, tenant):
        """Cross-reference CRUD (reference: handlers_objects.go
        /v1/objects/{class}/{id}/references/{prop}): POST appends a
        beacon, PUT replaces all, DELETE removes one."""
        col = self.db.get_collection(class_name)
        if col.config.property(prop) is None or \
                col.config.property(prop).data_type != "cref":
            raise ApiError(422, f"property {prop!r} of {class_name} is not "
                           "a reference property")
        def beacon_of(b):
            beacon = b.get("beacon") if isinstance(b, dict) else b
            if not isinstance(beacon, str) or not beacon:
                raise ApiError(422, "reference payload needs a 'beacon' "
                               "string")
            return beacon

        # read-modify-write under a per-uuid lock: two concurrent reference
        # additions to the same object must not lose each other's append,
        # but a slow replica in the replicated put must not block the whole
        # collection (see Collection.uuid_lock)
        with col.uuid_lock(uuid):
            obj = col.get_object(uuid, tenant=tenant)
            if obj is None:
                raise ApiError(404, f"object {uuid} not found")
            refs = list(obj.properties.get(prop) or [])
            if method == "POST":
                refs.append({"beacon": beacon_of(body or {})})
            elif method == "PUT":
                items = body if isinstance(body, list) else [body or {}]
                refs = [{"beacon": beacon_of(b)} for b in items]
            elif method == "DELETE":
                want = beacon_of(body or {})
                refs = [r for r in refs
                        if (r.get("beacon") if isinstance(r, dict)
                            else str(r)) != want]
            else:
                raise KeyError("references")
            props = dict(obj.properties)
            props[prop] = refs
            col.put_object(props, vector=obj.vector,
                           vectors=obj.vectors or None, uuid=uuid,
                           tenant=tenant,
                           creation_time_ms=obj.creation_time_ms)
        return 200, None

    def _batch_delete(self, body: dict, params: dict):
        """DELETE /v1/batch/objects (reference: handlers_batch_delete —
        {"match": {"class", "where"}, "dryRun", "output"})."""
        from weaviate_tpu.filters.filters import Filter

        match = body.get("match") or {}
        class_name = match.get("class", "")
        where = match.get("where")
        if not class_name or where is None:
            raise ApiError(422, "batch delete needs match.class and "
                           "match.where")
        col = self.db.get_collection(class_name)
        try:
            where_f = Filter.from_dict(where)
        except (KeyError, ValueError, TypeError) as e:
            raise ApiError(422, f"invalid match.where filter: {e}")
        result = col.batch_delete(
            where_f,
            tenant=params.get("tenant") or body.get("tenant"),
            dry_run=bool(body.get("dryRun")),
            verbose=body.get("output") == "verbose",
            consistency=params.get("consistency_level", "QUORUM"))
        return 200, {
            "match": match,
            "output": body.get("output", "minimal"),
            "dryRun": bool(body.get("dryRun")),
            "results": {
                "matches": result["matches"],
                "successful": result["successful"],
                "failed": result["failed"],
                # reference shape: null unless output=verbose
                "objects": result.get("objects")
                if body.get("output") == "verbose" else None,
            },
        }

    def _batch_references(self, body: list):
        """POST /v1/batch/references (reference: handlers_batch —
        [{from: weaviate://localhost/Class/uuid/prop, to: beacon}])."""
        if not isinstance(body, list):
            raise ApiError(422, "batch references payload must be a list")
        results = []
        for item in body:
            try:
                if not isinstance(item, dict):
                    raise ValueError("each reference must be an object "
                                     "with 'from' and 'to'")
                src = item.get("from", "")
                parts = [p for p in src.split("/") if p]
                # weaviate:, localhost, Class, uuid, prop
                if len(parts) < 4:
                    raise ValueError(f"malformed 'from' beacon {src!r}")
                cls, uid, prop = parts[-3], parts[-2], parts[-1]
                to = item.get("to")
                if not isinstance(to, str) or not to:
                    raise ValueError("'to' must be a beacon string")
                col = self.db.get_collection(cls)
                pcfg = col.config.property(prop)
                if pcfg is None or pcfg.data_type != "cref":
                    raise ValueError(
                        f"property {prop!r} of {cls} is not a reference "
                        "property")
                with col.uuid_lock(uid):  # see _references: no lost appends
                    obj = col.get_object(uid, tenant=item.get("tenant"))
                    if obj is None:
                        raise ValueError(f"source object {uid} not found")
                    refs = list(obj.properties.get(prop) or [])
                    refs.append({"beacon": to})
                    props = dict(obj.properties)
                    props[prop] = refs
                    col.put_object(props, vector=obj.vector,
                                   vectors=obj.vectors or None, uuid=uid,
                                   tenant=item.get("tenant"),
                                   creation_time_ms=obj.creation_time_ms)
                results.append({"result": {"status": "SUCCESS"}})
            except (KeyError, ValueError) as e:
                results.append({"result": {
                    "status": "FAILED",
                    "errors": {"error": [{"message": str(e)}]}}})
        return 200, results

    def _backups(self, method: str, seg: list[str], body):
        """Reference routes (handlers_backup.go):
        POST /v1/backups/{backend}            start backup
        GET  /v1/backups/{backend}/{id}       backup status
        POST /v1/backups/{backend}/{id}/restore    start restore
        GET  /v1/backups/{backend}/{id}/restore    restore status
        """
        from weaviate_tpu.backup import BackupError
        from weaviate_tpu.modules.base import ModuleError

        if self.backup_manager is None:
            raise ApiError(422, "backups require a module provider")
        if method == "POST" and len(seg) == 1:
            from weaviate_tpu.api.validation import BACKUP, validate_body

            validate_body(BACKUP, body or {}, "backup")
        try:
            if len(seg) == 1 and method == "POST":
                b = body or {}
                return 200, self.backup_manager.start_backup(
                    seg[0], b.get("id", ""), include=b.get("include"),
                    exclude=b.get("exclude"))
            if len(seg) == 2 and method == "GET":
                return 200, self.backup_manager.backup_status(seg[0], seg[1])
            if len(seg) == 3 and seg[2] == "restore":
                if method == "POST":
                    b = body or {}
                    return 200, self.backup_manager.start_restore(
                        seg[0], seg[1], include=b.get("include"),
                        exclude=b.get("exclude"))
                if method == "GET":
                    return 200, self.backup_manager.restore_status(
                        seg[0], seg[1])
        except (BackupError, ModuleError) as e:
            raise ApiError(422, str(e))
        raise KeyError("/v1/backups/" + "/".join(seg))

    def _debug(self, seg: list[str], params: dict):
        """The /v1/debug surface. ``GET /v1/debug`` is the index: every
        endpoint in :data:`DEBUG_ENDPOINTS` with a one-line description
        (the same table this dispatcher routes by, so listing and
        serving cannot drift apart)."""
        if not seg:
            return 200, {"endpoints": [
                {"path": f"/v1/debug/{name}", "description": desc}
                for name, desc in sorted(DEBUG_ENDPOINTS.items())]}
        name = seg[0]
        if seg[1:] or name not in DEBUG_ENDPOINTS:
            raise KeyError("/v1/debug/" + "/".join(seg))
        if name == "memory":
            return 200, self._debug_memory()
        if name == "storage":
            return 200, self._debug_storage()
        if name == "replication":
            return 200, self._debug_replication()
        if name == "perf":
            # last benchkeeper gate verdict + per-section trend deltas
            # (tools/benchkeeper persists the artifact; perfgate loads
            # it and republishes the weaviate_tpu_bench_* gauges)
            from weaviate_tpu.runtime import perfgate

            return 200, perfgate.snapshot()
        if name == "slo":
            # objectives + sliding-window burn rates (refreshes the
            # weaviate_tpu_slo_burn_rate gauges + incident sweep)
            return 200, tailboard.debug_slo()
        if name == "flight":
            # dispatch-record ring + structured slowlog + snapshots
            return 200, tailboard.debug_flight()
        if name == "kernelscope":
            # device-time truth plane: compiled-variant residency
            # EWMAs, memcpy model, per-tenant meters, capture index
            from weaviate_tpu.runtime import kernelscope

            return 200, kernelscope.snapshot()
        if name == "drift":
            # online drift plane: gate verdict + findings + canary and
            # live-telemetry trends (runtime/driftwatch.py)
            from weaviate_tpu.runtime import driftwatch

            return 200, driftwatch.snapshot()
        if name == "profile":
            # paramless: cheap — list persisted captures only. A
            # capture is an explicit ?ms=N opt-in (the paramless form
            # is exercised by the debug-index round-trip test and must
            # never spin the profiler).
            from weaviate_tpu.runtime import kernelscope

            if "id" in params:
                cap = kernelscope.load_capture(params["id"])
                if cap is None:
                    raise KeyError("/v1/debug/profile?id=" + params["id"])
                return 200, cap
            if "ms" in params:
                try:
                    ms = int(params["ms"])
                except ValueError:
                    raise ApiError(422, "ms must be an integer")
                if not 0 < ms <= 10_000:
                    raise ApiError(422, "ms must be in (0, 10000]")
                return 200, kernelscope.capture_profile(ms)
            return 200, {"captures": kernelscope.list_captures()}
        # traces: the finished-trace ring (tracing tentpole; sampled
        # traces carry device_ms attribution), or — ?tail=true — the
        # tail-retained ring the keep-at-completion decision feeds
        try:
            limit = int(params.get("limit", 50))
        except ValueError:
            raise ApiError(422, "limit must be an integer")
        if params.get("tail") == "true":
            return 200, {"traces": tailboard.tail_traces(limit)}
        return 200, {"traces": tracing.recent_traces(limit)}

    def _debug_memory(self) -> dict:
        """GET /v1/debug/memory: the HBM ledger's labeled breakdown —
        top allocations, per-collection rollup, and (when the backend
        exposes allocator stats) the allocator-vs-ledger delta. The
        ledger counts labeled data arrays only; the delta is
        executables beyond the estimate, replication overhead, and XLA
        scratch."""
        from weaviate_tpu.runtime.hbm_ledger import ledger
        from weaviate_tpu.runtime.memwatch import device_memory_stats

        from weaviate_tpu.parallel.mesh import host_count

        snap = ledger.snapshot()
        # per-MESH-HOST device bytes (hierarchical sharding attribution)
        # — distinct from each collection's host-RAM-tier "hostBytes"
        snap["hbmHostBytes"] = ledger.host_rollup(
            host_count(getattr(self.db, "mesh", None)))
        mw = getattr(self.db, "memwatch", None)
        budget = mw.device_budget() if mw is not None else None
        out = {
            "ledger": {**snap, "budgetBytes": budget},
            "allocator": device_memory_stats(),
        }
        if mw is not None:
            out["pressure"] = mw.under_pressure
            out["highWatermark"] = mw.high_watermark
            out["lowWatermark"] = mw.low_watermark
        deltas = {}
        for dev, stats in out["allocator"].items():
            if stats.get("bytesInUse") is not None:
                deltas[dev] = int(stats["bytesInUse"]) - snap["totalBytes"]
        if deltas:
            out["allocatorDelta"] = deltas
        return out

    def _debug_storage(self) -> dict:
        """GET /v1/debug/storage: per-bucket crash-recovery reports
        (frames replayed, torn-tail bytes truncated, WALs/segments
        quarantined, segments recovered) filed at every bucket open,
        plus the effective durability config. The crashtest harness
        (tools/crashtest) asserts a non-empty report here after every
        kill-restart cycle; the same registry feeds the
        weaviate_tpu_recovery_* counters."""
        from weaviate_tpu.storage import recovery

        out = recovery.snapshot()
        out["config"] = {
            "syncWal": bool(getattr(self.db, "sync_wal", False)),
            # the raft bucket ignores syncWal — pinned durable
            "raftBucketPinnedSync": self.node is not None,
        }
        return out

    def _debug_replication(self) -> dict:
        """GET /v1/debug/replication: anti-entropy convergence state —
        per-shard last-beat age, rounds run, entries reconciled, last
        diff size and divergence estimate, plus read-path divergence
        observations and any armed partition topology (what the
        clusterchaos checker watches while replicas heal). The same
        registry feeds weaviate_tpu_hashbeat_rounds_total and
        weaviate_tpu_replica_divergent_entries."""
        from weaviate_tpu.replication.hashbeater import replication_status
        from weaviate_tpu.runtime import faultline as _faultline

        out = replication_status.snapshot()
        # staged-2PC visibility: live (un-committed, un-aborted) entries
        # per loaded shard and how many the TTL path expired
        staged = {}
        for cname in self.db.list_collections():
            col = self.db.get_collection(cname)
            with col._lock:
                items = sorted(col.shards.items())
            for sname, shard in items:
                st = shard.staged_status()
                if st["staged"] or st["expired_total"]:
                    staged[f"{cname}/{sname}"] = st
        out["staged"] = staged
        topo = _faultline.topology_snapshot()
        if topo:
            out["partitions"] = topo  # armed topology faults (chaos runs)
        return out

    def _local_shard_details(self) -> list[dict]:
        """Per-shard breakdown for ?output=verbose (reference:
        nodes/handler.go verbose output with shard object counts), plus
        each shard's ledger-attributed device bytes."""
        from weaviate_tpu.runtime.hbm_ledger import ledger

        out = []
        for cname in self.db.list_collections():
            col = self.db.get_collection(cname)
            with col._lock:  # writers load shards concurrently
                items = sorted(col.shards.items())
            for sname, shard in items:
                out.append({
                    "name": sname, "class": cname,
                    "objectCount": shard.object_count(),
                    "vectorIndexingStatus": "READONLY"
                    if shard.read_only else "READY",
                    "vectorQueueLength": sum(
                        q.size() for q in shard._index_queues.values()),
                    "hbmBytes": ledger.shard_bytes(cname, sname),
                })
        return out

    def _nodes_payload(self, verbose: bool = False) -> list[dict]:
        if self.node is not None:
            infos = self.node.membership.nodes()
            # gossip states → the reference's node-status vocabulary
            # (entities/models.NodeStatus: HEALTHY/UNHEALTHY/UNAVAILABLE)
            status_map = {"alive": "HEALTHY", "suspect": "UNHEALTHY",
                          "dead": "UNAVAILABLE", "left": "UNAVAILABLE"}
            nodes = [{
                "name": i.name,
                "status": status_map.get(i.status.lower(),
                                         i.status.upper()),
                "version": VERSION,
                "stats": i.meta,
            } for i in sorted(infos.values(), key=lambda x: x.name)]
            from weaviate_tpu.runtime.memwatch import (
                device_memory_stats,
            )

            from weaviate_tpu.runtime.hbm_ledger import ledger

            from weaviate_tpu.parallel.mesh import host_count

            local_health = degrade.health()
            for n in nodes:
                if n["name"] == self.db.local_node:
                    n["stats"] = {**(n.get("stats") or {}),
                                  "deviceMemory": device_memory_stats(),
                                  "hbmLedgerBytes": ledger.total_bytes(),
                                  # per-mesh-host rollup (sums to
                                  # hbmLedgerBytes — ROADMAP item 2)
                                  "hbmHostBytes": ledger.host_rollup(
                                      host_count(self.db.mesh))}
                    # component health (degrade registry): a faulted
                    # batcher/native-plane dispatch path flips this
                    n["health"] = local_health
                    if not local_health["healthy"]:
                        n["status"] = "UNHEALTHY"
                    if verbose:
                        # shard details are known for THIS node (remote
                        # breakdowns would need an RPC fan-out, as in the
                        # reference)
                        n["shards"] = self._local_shard_details()
            return nodes
        shard_count = sum(len(c.shards) for c in self.db.collections.values())
        object_count = sum(
            s.object_count() for c in self.db.collections.values()
            for s in c.shards.values())
        from weaviate_tpu.parallel.mesh import host_count
        from weaviate_tpu.runtime.hbm_ledger import ledger
        from weaviate_tpu.runtime.memwatch import device_memory_stats

        local_health = degrade.health()
        node = {"name": self.db.local_node,
                "status": "HEALTHY" if local_health["healthy"]
                else "UNHEALTHY",
                "version": VERSION,
                "health": local_health,
                "stats": {"shardCount": shard_count,
                          "objectCount": object_count,
                          "deviceMemory": device_memory_stats(),
                          "hbmLedgerBytes": ledger.total_bytes(),
                          "hbmHostBytes": ledger.host_rollup(
                              host_count(self.db.mesh))}}
        if verbose:
            node["shards"] = self._local_shard_details()
        return [node]

    # -- /v1/schema -----------------------------------------------------------

    def _schema(self, method: str, seg: list[str], body):
        if not seg:
            if method == "GET":
                return 200, {"classes": [
                    class_to_wire(self.db.get_collection(n).config)
                    for n in self.db.list_collections()]}
            if method == "POST":
                from weaviate_tpu.api.validation import (SCHEMA_CLASS,
                                                         validate_body)

                validate_body(SCHEMA_CLASS, body or {}, "class")
                cfg = config_from_json(body or {})
                self.schema_target.create_collection(cfg)
                return 200, class_to_wire(cfg)
        elif len(seg) == 1:
            name = seg[0]
            if method == "GET":
                return 200, class_to_wire(self.db.get_collection(name).config)
            if method == "PUT":
                # update mutable class config (reference: PUT /v1/schema/{c}).
                # PARTIAL update semantics: only sections present in the
                # body overlay the current config — parsing the body alone
                # would fill omitted fields with defaults and silently
                # reset them (e.g. replication factor back to 1).
                import copy

                d = dict(body or {})
                d.setdefault("class", name)
                parsed = config_from_json(d)
                if parsed.name != name:
                    raise ApiError(422, "class name in body does not match "
                                   "the path")
                merged = copy.deepcopy(
                    self.db.get_collection(name).config)
                if "description" in d:
                    merged.description = parsed.description
                if "invertedIndexConfig" in d or "inverted" in d:
                    merged.inverted = parsed.inverted
                if "replicationConfig" in d or "replication" in d:
                    merged.replication = parsed.replication
                if "moduleConfig" in d or "module_config" in d:
                    merged.module_config = parsed.module_config
                if "multiTenancyConfig" in d or "multi_tenancy" in d:
                    merged.multi_tenancy = parsed.multi_tenancy
                if any(k in d for k in ("vectorizer", "vectorIndexType",
                                        "vectorIndexConfig",
                                        "vectorConfig", "vectors")):
                    merged.vectors = parsed.vectors
                self.schema_target.update_collection(merged)
                return 200, class_to_wire(self.db.get_collection(name).config)
            if method == "DELETE":
                self.schema_target.delete_collection(name)
                return 200, None
        elif len(seg) == 2 and seg[1] == "shards" and method == "GET":
            col = self.db.get_collection(seg[0])
            out = []
            for shard_name in col.sharding.shard_names:
                if not col._is_local(shard_name):
                    out.append({"name": shard_name, "status": "REMOTE",
                                "vectorQueueSize": 0})
                    continue
                if col.sharding.status_of(shard_name) == "COLD":
                    # deactivated tenants stay on disk — loading them for
                    # a status listing would defeat the offload
                    out.append({"name": shard_name, "status": "COLD",
                                "vectorQueueSize": 0})
                    continue
                shard = col._load_shard(shard_name)
                qsize = sum(q.size() for q in shard._index_queues.values())
                out.append({
                    "name": shard_name,
                    "status": "READONLY" if shard.read_only else "READY",
                    "vectorQueueSize": qsize,
                })
            return 200, out
        elif len(seg) == 3 and seg[1] == "shards" and method == "PUT":
            col = self.db.get_collection(seg[0])
            status = (body or {}).get("status", "").upper()
            if status not in ("READY", "READONLY"):
                raise ApiError(422, "shard status must be READY or READONLY")
            if seg[2] not in col.sharding.shard_names or \
                    not col._is_local(seg[2]):
                raise ApiError(404, f"shard {seg[2]!r} is not local")
            if col.sharding.status_of(seg[2]) == "COLD":
                raise ApiError(422, f"tenant shard {seg[2]!r} is COLD; "
                               "activate it before changing shard status")
            col._load_shard(seg[2]).set_read_only(status == "READONLY")
            return 200, {"status": status}
        elif len(seg) == 2 and seg[1] == "properties" and method == "POST":
            prop = property_from_json(body or {})
            self.schema_target.add_property(seg[0], prop)
            return 200, body
        elif len(seg) == 2 and seg[1] == "tenants":
            name = seg[0]
            col = self.db.get_collection(name)
            if method == "GET":
                return 200, [
                    {"name": t,
                     "activityStatus": col.sharding.status_of(t)}
                    for t in col.tenants()]
            if method == "PUT":
                # HOT/COLD offload (reference: PUT tenants with
                # activityStatus)
                tenants = [t if isinstance(t, dict) else {"name": t}
                           for t in (body or [])]
                self.schema_target.update_tenant_status(name, tenants)
                return 200, [
                    {"name": t["name"],
                     "activityStatus": col.sharding.status_of(t["name"])}
                    for t in tenants]
            tenants = [t["name"] if isinstance(t, dict) else t
                       for t in (body or [])]
            if method == "POST":
                self.schema_target.add_tenants(name, tenants)
                return 200, [{"name": t} for t in tenants]
            if method == "DELETE":
                self.schema_target.remove_tenants(name, tenants)
                return 200, None
        raise KeyError("/v1/schema/" + "/".join(seg))

    # -- /v1/objects ----------------------------------------------------------

    def _objects(self, method: str, seg: list[str], params: dict, body):
        tenant = params.get("tenant")
        # collection/tenant identity for the always-on phase histograms
        # (label values pass the tailboard's top-K cardinality guard)
        if len(seg) >= 2 and seg[0] != "validate":
            tailboard.annotate(collection=seg[0], tenant=tenant)
        elif tenant:
            tailboard.annotate(tenant=tenant)
        if not seg:
            if method == "GET":
                return self._list_objects(params)
            if method == "POST":
                return self._put_object(body or {}, tenant)
        elif len(seg) == 1 and seg[0] != "validate":
            # deprecated class-less route (reference: /v1/objects/{id}
            # scans classes; kept for old clients)
            uuid = seg[0]
            consistency = params.get("consistency_level")
            for cname in self.db.list_collections():
                col = self.db.get_collection(cname)
                if col.config.multi_tenancy.enabled:
                    continue  # tenant-less lookup cannot address MT data
                try:
                    obj = col.get_object(uuid, consistency=consistency)
                except Exception:
                    # one unhealthy, unrelated class must not break the
                    # scan for an object living elsewhere
                    continue
                if obj is None:
                    continue
                # resolve the class, delegate to the modern class-scoped
                # handler so consistency/result semantics stay identical
                return self._objects(method, [cname, uuid], params, body)
            raise ApiError(404, f"object {uuid} not found in any class")
        elif seg == ["validate"] and method == "POST":
            # dry-run validation (reference: POST /v1/objects/validate)
            b = dict(body or {})
            cls = b.get("class", "")
            col = self.db.get_collection(cls)
            props = b.get("properties") or {}
            for key in props:
                if col.config.property(key) is None:
                    raise ApiError(422, f"property {key!r} is not part of "
                                   f"class {cls}")
            vec = b.get("vector")
            if vec is not None and not isinstance(vec, list):
                raise ApiError(422, "vector must be a number array")
            return 200, None
        elif len(seg) == 4 and seg[2] == "references":
            return self._references(method, seg[0], seg[1], seg[3], body,
                                    tenant)
        elif len(seg) == 2:
            class_name, uuid = seg
            col = self.db.get_collection(class_name)
            if method in ("GET", "HEAD"):
                consistency = params.get("consistency_level")
                obj = col.get_object(uuid, tenant=tenant,
                                     consistency=consistency)
                if obj is None:
                    raise ApiError(404, f"object {uuid} not found")
                return 200, object_to_json(class_name, obj, tenant=tenant)
            if method in ("PUT", "PATCH"):
                body = dict(body or {})
                body.setdefault("class", class_name)
                body["id"] = uuid
                if method == "PATCH":
                    # merge is a read-modify-write: serialize against
                    # concurrent reference appends / PATCHes of the same
                    # object (same per-uuid lock as _references)
                    with col.uuid_lock(uuid):
                        return self._patch_merge(col, uuid, body, tenant)
                return self._put_object(body, tenant)
            if method == "DELETE":
                deleted = col.delete_object(
                    uuid, tenant=tenant,
                    consistency=params.get("consistency_level", "QUORUM"))
                if not deleted:
                    raise ApiError(404, f"object {uuid} not found")
                return 204, None
        raise KeyError("/v1/objects/" + "/".join(seg))

    def _put_object(self, body: dict, tenant: str | None):
        from weaviate_tpu.api.validation import OBJECT, validate_body

        validate_body(OBJECT, body or {}, "object")
        class_name = body.get("class") or body.get("collection")
        if not class_name:
            raise ApiError(422, "object is missing a class")
        tailboard.annotate(collection=class_name,
                           tenant=tenant or body.get("tenant"))
        col = self.db.get_collection(class_name)
        spec = {"properties": body.get("properties", {}),
                "vector": body.get("vector"), "vectors": body.get("vectors")}
        if self.modules is not None:
            self.modules.vectorize_batch(col.config, [spec])
        uuid = col.put_object(
            spec["properties"],
            vector=spec.get("vector"),
            vectors=spec.get("vectors"),
            uuid=body.get("id"),
            tenant=tenant or body.get("tenant"),
            creation_time_ms=int(body.get("creationTimeUnix") or 0),
        )
        eff_tenant = tenant or body.get("tenant")
        obj = col.get_object(uuid, tenant=eff_tenant)
        return 200, object_to_json(class_name, obj, tenant=eff_tenant)

    def _list_objects(self, params: dict):
        class_name = params.get("class")
        if not class_name:
            raise ApiError(422, "listing requires ?class=")
        col = self.db.get_collection(class_name)
        limit = int(params.get("limit", 25))
        offset = int(params.get("offset", 0))
        sort = None
        if params.get("sort"):
            orders = (params.get("order") or "asc").split(",")
            paths = params["sort"].split(",")
            sort = [{"path": p, "order": orders[min(i, len(orders) - 1)]}
                    for i, p in enumerate(paths)]
        where = None
        if params.get("where"):
            where = Filter.from_dict(json.loads(params["where"]))
        objs = col.fetch_objects(limit=limit, offset=offset, sort=sort,
                                 where=where, tenant=params.get("tenant"),
                                 after=params.get("after"))
        return 200, {
            "objects": [object_to_json(class_name, o,
                                       tenant=params.get("tenant"))
                        for o in objs],
            "totalResults": len(objs),
        }

    # -- /v1/batch/objects -----------------------------------------------------

    def _batch_objects(self, body: dict):
        from weaviate_tpu.api.validation import (BATCH_OBJECTS,
                                                 validate_body)

        validate_body(BATCH_OBJECTS, body or {}, "batch")
        objects = body.get("objects", [])
        # group by (class, tenant): one batch_put call writes to exactly one
        # tenant — grouping by class alone would land cross-tenant objects
        # in the first entry's tenant
        by_target: dict[tuple[str, str | None], list[tuple[int, dict]]] = {}
        for i, spec in enumerate(objects):
            cname = spec.get("class") or spec.get("collection") or ""
            by_target.setdefault((cname, spec.get("tenant")), []).append((i, spec))
        results: list[dict | None] = [None] * len(objects)
        for (cname, tenant), entries in by_target.items():
            try:
                col = self.db.get_collection(cname)
            except KeyError as e:
                for i, spec in entries:
                    results[i] = {"id": spec.get("id"), "result": {
                        "status": "FAILED", "errors": {"error": [
                            {"message": str(e)}]}}}
                continue
            specs = [{
                "uuid": spec.get("id"),
                "properties": spec.get("properties", {}),
                "vector": spec.get("vector"),
                "vectors": spec.get("vectors"),
            } for _i, spec in entries]
            if self.modules is not None:
                try:
                    self.modules.vectorize_batch(col.config, specs)
                except Exception as exc:  # per-object errors, not whole-batch
                    from weaviate_tpu.modules.provider import needs_vector

                    kept_entries, kept_specs = [], []
                    for (i, spec_body), spec in zip(entries, specs):
                        if needs_vector(col.config, spec):
                            results[i] = {"id": spec.get("uuid"), "result": {
                                "status": "FAILED", "errors": {"error": [
                                    {"message": f"vectorize: {exc}"}]}}}
                        else:
                            kept_entries.append((i, spec_body))
                            kept_specs.append(spec)
                    entries, specs = kept_entries, kept_specs
            outcomes = col.batch_put(specs, tenant=tenant)
            for (i, _spec), out in zip(entries, outcomes):
                if out["status"] == "SUCCESS":
                    results[i] = {"id": out["uuid"],
                                  "result": {"status": "SUCCESS"}}
                else:
                    results[i] = {"id": out.get("uuid"), "result": {
                        "status": "FAILED", "errors": {"error": [
                            {"message": out.get("error", "")}]}}}
        return 200, results


