"""GraphQL API: /v1/graphql Get / Aggregate / Explore.

Reference: adapters/handlers/graphql — the schema is generated at runtime
from the class schema (graphql/schema.go:98-109) and serves local/get
(class_builder_fields.go: nearVector/nearObject/nearText/bm25/hybrid/where/
sort/limit/autocut args, _additional properties), local/aggregate, and
local/explore. No GraphQL library ships in this environment, so this module
carries a small spec-subset lexer/parser (operations, selection sets,
arguments with object/list/enum/variable values, aliases, fragments are NOT
needed by the reference clients' query shapes) and executes directly
against the Database — schema validation happens against CollectionConfig
at execution time, the same information the reference bakes into its
generated schema.
"""

from __future__ import annotations

import json
import re

import numpy as np

from weaviate_tpu.modules.base import ModuleError

# ---------------------------------------------------------------------------
# Lexer / parser (GraphQL spec subset)
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>[\s,]+)
  | (?P<comment>\#[^\n\r]*)
  | (?P<spread>\.\.\.)
  | (?P<name>[_A-Za-z][_0-9A-Za-z]*)
  | (?P<float>-?\d+\.\d+([eE][+-]?\d+)?|-?\d+[eE][+-]?\d+)
  | (?P<int>-?\d+)
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<punct>[{}()\[\]:$!=])
    """,
    re.VERBOSE,
)

_JSON_ESCAPE_RE = re.compile(r"\\u([0-9a-fA-F]{4})|\\(.)")
_JSON_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f"}


def _unescape_fallback(m: re.Match) -> str:
    if m.group(1) is not None:
        return chr(int(m.group(1), 16))
    return _JSON_ESCAPES.get(m.group(2), m.group(2))


class GraphQLError(Exception):
    pass


def _tokenize(src: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise GraphQLError(f"unexpected character {src[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        out.append((kind, m.group(0)))
    out.append(("eof", ""))
    return out


class _Var:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name


class Field:
    __slots__ = ("name", "alias", "args", "selections")

    def __init__(self, name, alias=None, args=None, selections=None):
        self.name = name
        self.alias = alias or name
        self.args = args or {}
        self.selections = selections or []

    def sel(self, name: str) -> "Field | None":
        for f in self.selections:
            if isinstance(f, Field) and f.name == name:
                return f
        return None

    def fragments(self) -> "list[InlineFragment]":
        return [f for f in self.selections if isinstance(f, InlineFragment)]


class InlineFragment:
    """``... on ClassName { ... }`` — how the reference's GraphQL schema
    types cross-reference properties (class_builder_fields.go ref
    resolution)."""

    __slots__ = ("type_name", "selections")

    def __init__(self, type_name, selections):
        self.type_name = type_name
        self.selections = selections

    def sel(self, name: str):
        for f in self.selections:
            if isinstance(f, Field) and f.name == name:
                return f
        return None


class _Parser:
    def __init__(self, tokens):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, value):
        kind, v = self.next()
        if v != value:
            raise GraphQLError(f"expected {value!r}, got {v!r}")

    def parse_document(self) -> list[Field]:
        kind, v = self.peek()
        if v == "query":
            self.next()
            # optional operation name and variable definitions
            kind, v = self.peek()
            if kind == "name":
                self.next()
            if self.peek()[1] == "(":
                # skip variable definitions: ($x: Type = default, ...)
                depth = 0
                while True:
                    _, v = self.next()
                    if v == "(":
                        depth += 1
                    elif v == ")":
                        depth -= 1
                        if depth == 0:
                            break
        elif v == "mutation":
            raise GraphQLError("mutations are not supported")
        return self.parse_selection_set()

    def parse_selection_set(self) -> list[Field]:
        self.expect("{")
        fields = []
        while self.peek()[1] != "}":
            fields.append(self.parse_field())
        self.next()  # consume }
        return fields

    def parse_field(self) -> Field:
        kind, name = self.next()
        if kind == "spread":
            _, on = self.next()
            if on != "on":
                raise GraphQLError("only inline fragments ('... on Type') "
                                   "are supported")
            kind2, type_name = self.next()
            if kind2 != "name":
                raise GraphQLError("expected type name after '... on'")
            return InlineFragment(type_name, self.parse_selection_set())
        if kind != "name":
            raise GraphQLError(f"expected field name, got {name!r}")
        alias = None
        if self.peek()[1] == ":":
            self.next()
            kind2, real = self.next()
            if kind2 != "name":
                raise GraphQLError(f"expected field name after alias")
            alias, name = name, real
        args = {}
        if self.peek()[1] == "(":
            self.next()
            while self.peek()[1] != ")":
                _, key = self.next()
                self.expect(":")
                args[key] = self.parse_value()
            self.next()
        selections = []
        if self.peek()[1] == "{":
            selections = self.parse_selection_set()
        return Field(name, alias, args, selections)

    def parse_value(self):
        kind, v = self.next()
        if v == "$":
            _, name = self.next()
            return _Var(name)
        if v == "{":
            obj = {}
            while self.peek()[1] != "}":
                _, key = self.next()
                self.expect(":")
                obj[key] = self.parse_value()
            self.next()
            return obj
        if v == "[":
            arr = []
            while self.peek()[1] != "]":
                arr.append(self.parse_value())
            self.next()
            return arr
        if kind == "int":
            return int(v)
        if kind == "float":
            return float(v)
        if kind == "string":
            # The string grammar (see _TOKEN_RE) is JSON-compatible; json.loads
            # handles \uXXXX and backslash escapes without re-interpreting
            # UTF-8 bytes as Latin-1 the way unicode_escape would.
            try:
                return json.loads(v)
            except ValueError:
                # Literal control characters are legal for us but not JSON.
                return _JSON_ESCAPE_RE.sub(_unescape_fallback, v[1:-1])
        if kind == "name":
            if v == "true":
                return True
            if v == "false":
                return False
            if v == "null":
                return None
            return v  # enum — stays a bare string
        raise GraphQLError(f"unexpected value token {v!r}")


def parse_query(src: str) -> list[Field]:
    return _Parser(_tokenize(src)).parse_document()


def _resolve_vars(value, variables: dict):
    if isinstance(value, _Var):
        if value.name not in (variables or {}):
            raise GraphQLError(f"variable ${value.name} not provided")
        return variables[value.name]
    if isinstance(value, dict):
        return {k: _resolve_vars(v, variables) for k, v in value.items()}
    if isinstance(value, list):
        return [_resolve_vars(v, variables) for v in value]
    return value


# ---------------------------------------------------------------------------
# Execution
# ---------------------------------------------------------------------------


class _NearTextShim:
    """Duck-types the gRPC NearText proto for Provider.apply_moves."""

    class _Move:
        def __init__(self, d):
            self.concepts = d.get("concepts") or []
            objs = d.get("objects") or []
            self.uuids = [o.get("id") or o.get("beacon", "").split("/")[-1]
                          for o in objs]
            self.force = d.get("force", 0.0)

    def __init__(self, d: dict):
        self._moves = {}
        if d.get("moveTo"):
            self._moves["move_to"] = self._Move(d["moveTo"])
        if d.get("moveAwayFrom"):
            self._moves["move_away"] = self._Move(d["moveAwayFrom"])

    def HasField(self, name):
        return name in self._moves

    def __getattr__(self, name):
        try:
            return self._moves[name]
        except KeyError:
            raise AttributeError(name)


def _certainty_to_distance(c: float) -> float:
    # reference: certainty = 1 - d/2 for cosine (additional/certainty.go)
    return 2.0 * (1.0 - float(c))


def _distance_to_certainty(d: float) -> float:
    return 1.0 - float(d) / 2.0


class GraphQLExecutor:
    """Callable for RestServer(graphql_executor=...): payload dict
    {"query": ..., "variables": ...} -> GraphQL response dict."""

    def __init__(self, db, modules=None):
        self.db = db
        self.modules = modules

    def __call__(self, payload: dict) -> dict:
        try:
            query = payload.get("query") or ""
            variables = payload.get("variables") or {}
            roots = parse_query(query)
            data = {}
            for root in roots:
                if root.name == "Get":
                    data[root.alias] = self._get_root(root, variables)
                elif root.name == "Aggregate":
                    data[root.alias] = self._aggregate_root(root, variables)
                elif root.name == "Explore":
                    data[root.alias] = self._explore(root, variables)
                else:
                    raise GraphQLError(f"unknown root field {root.name!r}")
            return {"data": data}
        except (GraphQLError, ModuleError, KeyError, ValueError,
                TypeError) as e:
            msg = str(e) if str(e) else repr(e)
            return {"data": None, "errors": [{"message": msg}]}

    # -- Get -----------------------------------------------------------------

    def _get_root(self, root: Field, variables) -> dict:
        out = {}
        for cls_field in root.selections:
            if isinstance(cls_field, InlineFragment):
                raise GraphQLError(
                    "inline fragments are only supported inside "
                    "reference-property selections")
            out[cls_field.alias] = self._get_class(cls_field, variables)
        return out

    def _get_class(self, f: Field, variables) -> list[dict]:
        col = self.db.get_collection(f.name)
        args = {k: _resolve_vars(v, variables) for k, v in f.args.items()}
        limit = int(args.get("limit", 25))
        offset = int(args.get("offset", 0))
        tenant = args.get("tenant")
        # identity for the always-on phase histograms (tailboard top-K
        # guard clamps the label values)
        from weaviate_tpu.runtime import tailboard

        tailboard.annotate(collection=f.name, tenant=tenant)
        autocut = int(args.get("autocut", 0))
        where = self._parse_where(args.get("where"))
        k = limit + offset

        near_vec, vec_name, max_distance = self._resolve_near(
            col, args, tenant)
        if near_vec is not None:
            search = "vector"
        elif "bm25" in args:
            search = "bm25"
        elif "hybrid" in args:
            search = "hybrid"
        else:
            search = None

        if search == "vector":
            results = col.near_vector(
                near_vec, k=k, vec_name=vec_name, tenant=tenant,
                where=where, max_distance=max_distance, autocut=autocut)
        elif search == "bm25":
            d = args["bm25"]
            results = col.bm25(d.get("query", ""), k=k,
                               properties=d.get("properties"),
                               tenant=tenant, where=where, autocut=autocut)
        elif search == "hybrid":
            d = args["hybrid"]
            tv = d.get("targetVectors")
            hv = d.get("vector")
            if hv is None and self.modules is not None and d.get("query"):
                try:
                    hv = self.modules.vectorize_query(
                        col.config, d["query"], tv[0] if tv else "")
                except Exception:
                    hv = None  # degrade to sparse-only like the reference
            fusion = {"rankedFusion": "ranked",
                      "relativeScoreFusion": "relativeScore"}.get(
                          d.get("fusionType", ""), "relativeScore")
            results = col.hybrid(
                d.get("query", ""), vector=hv,
                alpha=float(d.get("alpha", 0.75)), k=k,
                properties=d.get("properties"),
                vec_name=tv[0] if tv else "", tenant=tenant,
                fusion=fusion, where=where, autocut=autocut)
        else:
            # plain listing (with optional sort / cursor)
            if "groupBy" in args:
                raise GraphQLError(
                    "groupBy requires a search argument (nearVector/"
                    "nearText/bm25/hybrid/...)")
            sort = args.get("sort")
            if sort is not None and not isinstance(sort, list):
                sort = [sort]
            objs = col.fetch_objects(
                limit=limit, offset=offset, tenant=tenant,
                sort=[{"path": s.get("path"), "order": s.get("order", "asc")}
                      for s in sort] if sort else None,
                where=where, after=args.get("after"))
            return [self._render_object(f, col, o, None, tenant)
                    for o in objs]

        sort = args.get("sort")
        if sort is not None:
            # sort composes with search results (reference sorter/
            # objects_sorter.go keeps the distance pairing through it)
            from weaviate_tpu.query.sorter import sort_search_results

            if not isinstance(sort, list):
                sort = [sort]
            results = sort_search_results(
                results,
                [{"path": s.get("path"), "order": s.get("order", "asc")}
                 for s in sort])
        results = results[offset:offset + limit]
        rerank_field = None
        add = f.sel("_additional")
        if add is not None:
            rerank_field = add.sel("rerank")
        if rerank_field is not None:
            results = self._apply_rerank(col, results, rerank_field.args)
        if "groupBy" in args:
            return self._render_grouped(f, col, results, args["groupBy"],
                                        tenant)
        return [self._render_result(f, col, r, tenant)
                for r in results]

    def _render_hit(self, f: Field, col, r, tenant) -> dict:
        """One groupBy hit, rendered through the query's
        group{hits{...}} selection set (falls back to id+distance when
        the query names no hit fields)."""
        add = f.sel("_additional")
        group_f = add.sel("group") if add is not None else None
        hits_f = group_f.sel("hits") if group_f is not None else None
        if hits_f is not None and hits_f.selections:
            return self._render_result(hits_f, col, r, tenant)
        return {"_additional": {"id": r.uuid, "distance": r.distance}}

    def _resolve_near(self, col, args: dict, tenant=None):
        """Resolve any near* argument to (vector, vec_name, max_distance);
        (None, "", None) when no near arg is present. One resolver for the
        Get and Aggregate roots so their semantics (named vectors,
        distance/certainty thresholds, nearText moves) cannot drift."""

        def _target(d):
            tv = d.get("targetVectors")
            return tv[0] if tv else ""

        def _max_dist(d):
            if "distance" in d:
                return float(d["distance"])
            if "certainty" in d:
                return _certainty_to_distance(d["certainty"])
            return None

        if "nearVector" in args:
            d = args["nearVector"]
            return (np.asarray(d["vector"], dtype=np.float32),
                    _target(d), _max_dist(d))
        if "nearObject" in args:
            d = args["nearObject"]
            uid = d.get("id") or d.get("beacon", "").split("/")[-1]
            anchor = col.get_object(uid, tenant=tenant)
            if anchor is None:
                raise GraphQLError(f"nearObject anchor {uid} not found")
            vec_name = _target(d)
            vec = (anchor.vectors.get(vec_name) if vec_name
                   else anchor.vector)
            if vec is None:
                raise GraphQLError(f"anchor {uid} has no vector")
            return vec, vec_name, _max_dist(d)
        if "nearText" in args:
            d = args["nearText"]
            if self.modules is None:
                raise GraphQLError("nearText requires a vectorizer module")
            vec_name = _target(d)
            vec = self.modules.vectorize_query(
                col.config, " ".join(d.get("concepts") or []), vec_name)
            vec = self.modules.apply_moves(
                col, vec, _NearTextShim(d), vec_name)
            return vec, vec_name, _max_dist(d)
        # near<Media>: vectorize through the class's multi2vec module
        for arg_name, kind in (("nearImage", "image"),
                               ("nearAudio", "audio"),
                               ("nearVideo", "video"),
                               ("nearThermal", "thermal"),
                               ("nearDepth", "depth"),
                               ("nearIMU", "imu")):
            if arg_name in args:
                if self.modules is None:
                    raise GraphQLError(
                        f"{arg_name} requires a multi2vec module")
                d = args[arg_name]
                vec_name = _target(d)
                vec = self.modules.vectorize_media(
                    col.config, kind, d.get(kind, ""), vec_name)
                return vec, vec_name, _max_dist(d)
        return None, "", None

    def _render_grouped(self, f: Field, col, results, group_by,
                        tenant) -> list[dict]:
        """Get-level groupBy (reference: groupBy{path groups
        objectsPerGroup} + _additional{group{...}}): one returned entry
        per group, hits nested under _additional.group."""
        path = group_by.get("path")
        prop = path[0] if isinstance(path, list) else path
        max_groups = max(int(group_by.get("groups", 5)), 1)
        per_group = max(int(group_by.get("objectsPerGroup", 5)), 1)
        groups: dict = {}
        order: list = []
        for r in results:
            obj = r.object or col.get_object(r.uuid, tenant=tenant)
            if obj is None:
                continue
            value = obj.properties.get(prop)
            key = tuple(value) if isinstance(value, list) else value
            try:
                hash(key)
            except TypeError:  # dict-typed / nested values
                key = repr(value)
            if key not in groups:
                if len(groups) >= max_groups:
                    continue
                groups[key] = []
                order.append(key)
            if len(groups[key]) < per_group:
                r.object = obj
                groups[key].append(r)
        out = []
        for gid, key in enumerate(order):
            hits = groups[key]
            best = hits[0]
            row = self._render_result(f, col, best, tenant)
            dists = [h.distance for h in hits if h.distance is not None]
            add = row.setdefault("_additional", {})
            add["group"] = {
                "id": gid,
                "groupedBy": {"value": key if not isinstance(key, tuple)
                              else list(key),
                              "path": [prop]},
                "count": len(hits),
                "minDistance": min(dists) if dists else None,
                "maxDistance": max(dists) if dists else None,
                "hits": [self._render_hit(f, col, h, tenant)
                         for h in hits],
            }
            out.append(row)
        return out

    def _apply_rerank(self, col, results, rr_args):
        if self.modules is None:
            raise GraphQLError("rerank requires a reranker module")
        prop = rr_args.get("property", "")
        docs = []
        for r in results:
            obj = r.object or col.get_object(r.uuid)
            docs.append(str((obj.properties if obj else {}).get(prop, "")))
        scores = self.modules.rerank(col.config, rr_args.get("query") or "",
                                     docs)
        for r, s in zip(results, scores):
            r.rerank_score = s
        results.sort(key=lambda r: -(r.rerank_score or 0.0))
        return results

    def _render_result(self, f: Field, col, r, tenant=None) -> dict:
        obj = r.object or col.get_object(r.uuid, tenant=tenant)
        return self._render_object(f, col, obj, r, tenant)

    def _render_object(self, f: Field, col, obj, result,
                       tenant=None) -> dict:
        out = {}
        for sel in f.selections:
            if isinstance(sel, InlineFragment):
                continue  # fragments only make sense under a ref property
            if sel.name == "_additional":
                out[sel.alias] = self._additional(sel, col, obj, result)
            elif obj is not None:
                value = obj.properties.get(sel.name)
                if sel.selections and isinstance(value, list):
                    out[sel.alias] = self._render_refs(sel, value, tenant)
                else:
                    out[sel.alias] = value
            else:
                out[sel.alias] = None
        return out

    def _render_refs(self, sel: Field, beacons: list,
                     tenant=None) -> list[dict]:
        """Resolve cross-reference beacons and render each target through
        the matching inline fragment (reference: ref-property fields are
        GraphQL union types over the target classes)."""
        out = []
        frags = {fr.type_name: fr for fr in sel.fragments()}
        for ref in beacons:
            beacon = ref.get("beacon", "") if isinstance(ref, dict) \
                else str(ref)
            parts = [p for p in beacon.split("/") if p]
            if len(parts) < 2:
                continue
            uid = parts[-1]
            cls_name = parts[-2] if len(parts) >= 3 and \
                parts[-2][0:1].isupper() else None
            candidates = [cls_name] if cls_name else \
                self.db.list_collections()
            for cname in candidates:
                try:
                    target_col = self.db.get_collection(cname)
                    # MT targets resolve within the query's tenant; a
                    # tenant-less lookup at an MT class is skipped, not
                    # fatal (ValueError from _check_tenant)
                    target = target_col.get_object(uid, tenant=tenant)
                except (KeyError, ValueError):
                    continue
                if target is None:
                    continue
                frag = frags.get(cname)
                if frag is None:
                    break  # resolved, but the query doesn't want this type
                row = self._render_object(
                    Field(sel.name, selections=frag.selections),
                    target_col, target, None, tenant)
                row["__typename"] = cname
                out.append(row)
                break
        return out

    def _additional(self, add: Field, col, obj, result) -> dict:
        out = {}
        for sel in add.selections:
            if isinstance(sel, InlineFragment):
                continue
            n = sel.name
            if n == "id":
                out[sel.alias] = obj.uuid if obj else (
                    result.uuid if result else None)
            elif n == "vector":
                v = obj.vector if obj is not None else None
                out[sel.alias] = None if v is None else np.asarray(v).tolist()
            elif n == "vectors":
                out[sel.alias] = {
                    k: np.asarray(v).tolist()
                    for k, v in (obj.vectors if obj else {}).items()}
            elif n == "distance":
                out[sel.alias] = None if result is None else result.distance
            elif n == "certainty":
                d = None if result is None else result.distance
                out[sel.alias] = None if d is None else _distance_to_certainty(d)
            elif n == "score":
                out[sel.alias] = None if result is None else result.score
            elif n == "rerank":
                rr = getattr(result, "rerank_score", None)
                out[sel.alias] = [{"score": rr}]
            elif n == "creationTimeUnix":
                out[sel.alias] = str(obj.creation_time_ms) if obj else None
            elif n == "lastUpdateTimeUnix":
                out[sel.alias] = str(obj.last_update_time_ms) if obj else None
            elif n == "generate":
                out[sel.alias] = self._generate(sel, col, obj)
            elif n == "answer":
                out[sel.alias] = self._answer(sel, col, obj)
            elif n == "tokens":
                out[sel.alias] = self._tokens(sel, col, obj)
            elif n == "summary":
                out[sel.alias] = self._summary(sel, col, obj)
            else:
                out[sel.alias] = None
        return out

    def _obj_text(self, col, obj, properties=None) -> str:
        props = obj.properties if obj is not None else {}
        keys = properties or [p.name for p in col.config.properties
                              if p.data_type in ("text", "text[]")]
        parts = []
        for key in keys:
            v = props.get(key)
            if isinstance(v, str):
                parts.append(v)
            elif isinstance(v, list):
                parts.extend(x for x in v if isinstance(x, str))
        return " ".join(parts)

    def _answer(self, sel: Field, col, obj) -> dict:
        if self.modules is None:
            raise GraphQLError("answer requires a qna module")
        question = sel.args.get("question", "")
        props = sel.args.get("properties")
        text = self._obj_text(col, obj, props)
        ans = self.modules.answer(col.config, text, question)
        ans.setdefault("result", ans.get("answer"))
        return ans

    def _tokens(self, sel: Field, col, obj) -> list[dict]:
        if self.modules is None:
            raise GraphQLError("tokens requires a ner module")
        props = sel.args.get("properties")
        return self.modules.ner(col.config,
                                self._obj_text(col, obj, props))

    def _summary(self, sel: Field, col, obj) -> list[dict]:
        if self.modules is None:
            raise GraphQLError("summary requires a sum module")
        props = sel.args.get("properties")
        return self.modules.summarize(col.config,
                                      self._obj_text(col, obj, props))

    def _generate(self, sel: Field, col, obj) -> dict:
        if self.modules is None:
            raise GraphQLError("generate requires a generative module")
        res = {}
        props = obj.properties if obj is not None else {}
        args = sel.args
        if "singleResult" in args:
            prompt = (args["singleResult"] or {}).get("prompt", "")
            res["singleResult"] = self.modules.generate_single(
                col.config, prompt, props)
        if "groupedResult" in args:
            task = (args["groupedResult"] or {}).get("task", "")
            res["groupedResult"] = self.modules.generate_grouped(
                col.config, task, [props])
        res["error"] = None
        return res

    # -- Aggregate -----------------------------------------------------------

    def _aggregate_root(self, root: Field, variables) -> dict:
        out = {}
        for cls_field in root.selections:
            if isinstance(cls_field, InlineFragment):
                raise GraphQLError(
                    "inline fragments are only supported inside "
                    "reference-property selections")
            out[cls_field.alias] = self._aggregate_class(cls_field, variables)
        return out

    def _aggregate_class(self, f: Field, variables):
        col = self.db.get_collection(f.name)
        args = {k: _resolve_vars(v, variables) for k, v in f.args.items()}
        where = self._parse_where(args.get("where"))
        tenant = args.get("tenant")
        group_by = args.get("groupBy")
        if isinstance(group_by, list):
            group_by = group_by[0] if group_by else None
        near_vec, near_vec_name, near_max_dist = self._resolve_near(
            col, args, tenant)

        props, requested = [], {}
        wants_grouped = False
        for sel in f.selections:
            if sel.name in ("meta", "groupedBy"):
                wants_grouped = wants_grouped or sel.name == "groupedBy"
                continue
            props.append(sel.name)
            metrics = []
            for m in sel.selections:
                metrics.append(m.name)
            requested[sel.name] = metrics or None

        agg = col.aggregate(properties=props or None, group_by=group_by,
                            where=where, tenant=tenant, requested=requested,
                            near_vector=near_vec,
                            near_vec_name=near_vec_name,
                            near_max_distance=near_max_dist,
                            object_limit=args.get("objectLimit"))

        def render(meta_count, properties, grouped_value=None):
            row = {}
            for sel in f.selections:
                if sel.name == "meta":
                    row[sel.alias] = {"count": meta_count}
                elif sel.name == "groupedBy":
                    row[sel.alias] = {"value": grouped_value,
                                      "path": [group_by] if group_by else []}
                else:
                    row[sel.alias] = properties.get(sel.name)
            return row

        if group_by:
            return [render(g["meta"]["count"], g["properties"],
                           g["groupedBy"]["value"])
                    for g in agg.get("groups", [])]
        return [render(agg["meta"]["count"], agg["properties"])]

    # -- Explore ---------------------------------------------------------------

    def _explore(self, root: Field, variables) -> list[dict]:
        args = {k: _resolve_vars(v, variables) for k, v in root.args.items()}
        limit = int(args.get("limit", 20))
        hits = []
        for name in self.db.list_collections():
            col = self.db.get_collection(name)
            if "nearVector" in args:
                vec = np.asarray(args["nearVector"]["vector"],
                                 dtype=np.float32)
            elif "nearText" in args:
                if self.modules is None:
                    raise GraphQLError("nearText requires a vectorizer")
                try:
                    vec = self.modules.vectorize_query(
                        col.config, " ".join(args["nearText"].get(
                            "concepts") or []), "")
                except Exception:
                    continue  # class without a vectorizer: skip
            else:
                raise GraphQLError("Explore requires nearVector or nearText")
            try:
                for r in col.near_vector(vec, k=limit,
                                         include_objects=False):
                    hits.append((r.distance, name, r.uuid))
            except Exception:
                continue  # dimension mismatch etc.
        hits.sort(key=lambda h: h[0])
        out = []
        for dist, cls, uid in hits[:limit]:
            row = {}
            for sel in root.selections:
                if sel.name == "beacon":
                    row[sel.alias] = f"weaviate://localhost/{cls}/{uid}"
                elif sel.name == "className":
                    row[sel.alias] = cls
                elif sel.name == "distance":
                    row[sel.alias] = dist
                elif sel.name == "certainty":
                    row[sel.alias] = _distance_to_certainty(dist)
                else:
                    row[sel.alias] = None
            out.append(row)
        return out

    # -- helpers ---------------------------------------------------------------

    @staticmethod
    def _parse_where(w):
        if w is None:
            return None
        from weaviate_tpu.filters.filters import Filter

        return Filter.from_dict(w)
