"""gRPC v1 service implementation.

Reference: adapters/handlers/grpc/v1/service.go (Search :173, BatchObjects
:126), parse_search_request.go (proto -> search params), prepare_reply.go
(results -> proto). One unary-unary handler per RPC; request parsing and
reply marshalling live next to each other per RPC, mirroring the
reference's parse/prepare split.
"""

from __future__ import annotations

import logging
import time
import uuid as _uuid
from concurrent.futures import ThreadPoolExecutor

import grpc
import numpy as np
from google.protobuf import json_format

from weaviate_tpu.api.grpc import v1_pb2 as pb
from weaviate_tpu.filters.filters import Filter, Operator
from weaviate_tpu.schema.config import DataType

logger = logging.getLogger(__name__)

_SERVICE = "weaviate.v1.Weaviate"

_CONSISTENCY = {
    pb.CONSISTENCY_LEVEL_UNSPECIFIED: "QUORUM",
    pb.CONSISTENCY_LEVEL_ONE: "ONE",
    pb.CONSISTENCY_LEVEL_QUORUM: "QUORUM",
    pb.CONSISTENCY_LEVEL_ALL: "ALL",
}

_OPERATORS = {
    pb.Filters.OPERATOR_EQUAL: Operator.EQUAL,
    pb.Filters.OPERATOR_NOT_EQUAL: Operator.NOT_EQUAL,
    pb.Filters.OPERATOR_GREATER_THAN: Operator.GREATER_THAN,
    pb.Filters.OPERATOR_GREATER_THAN_EQUAL: Operator.GREATER_THAN_EQUAL,
    pb.Filters.OPERATOR_LESS_THAN: Operator.LESS_THAN,
    pb.Filters.OPERATOR_LESS_THAN_EQUAL: Operator.LESS_THAN_EQUAL,
    pb.Filters.OPERATOR_AND: Operator.AND,
    pb.Filters.OPERATOR_OR: Operator.OR,
    pb.Filters.OPERATOR_WITHIN_GEO_RANGE: Operator.WITHIN_GEO_RANGE,
    pb.Filters.OPERATOR_LIKE: Operator.LIKE,
    pb.Filters.OPERATOR_IS_NULL: Operator.IS_NULL,
    pb.Filters.OPERATOR_CONTAINS_ANY: Operator.CONTAINS_ANY,
    pb.Filters.OPERATOR_CONTAINS_ALL: Operator.CONTAINS_ALL,
}


class ApiError(Exception):
    def __init__(self, code: grpc.StatusCode, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


# ---------------------------------------------------------------------------
# request parsing (reference: v1/parse_search_request.go)
# ---------------------------------------------------------------------------

def _vector_from(vector_bytes: bytes, vector_floats) -> np.ndarray | None:
    if vector_bytes:
        return np.frombuffer(vector_bytes, dtype="<f4").astype(np.float32)
    if len(vector_floats):
        return np.asarray(list(vector_floats), dtype=np.float32)
    return None


def filters_from_pb(f: "pb.Filters") -> Filter:
    op = _OPERATORS.get(f.operator)
    if op is None:
        raise ApiError(grpc.StatusCode.INVALID_ARGUMENT,
                       f"unknown filter operator {f.operator}")
    if op in (Operator.AND, Operator.OR):
        return Filter(op, operands=[filters_from_pb(c) for c in f.filters])
    # target path: new-style FilterTarget.property, else legacy 'on'
    path: list[str] | None = None
    which = f.target.WhichOneof("target")
    if which == "property":
        path = [f.target.property]
    elif which in ("single_target", "multi_target"):
        tgt = getattr(f.target, which)
        sub = tgt.target.WhichOneof("target")
        path = [tgt.on] + ([tgt.target.property] if sub == "property" else [])
    elif which == "count":
        path = [f.target.count.on]
    elif len(f.on):
        path = list(f.on)
    value_field = f.WhichOneof("test_value")
    value = None
    if value_field is not None:
        raw = getattr(f, value_field)
        if value_field in ("value_text_array", "value_int_array",
                          "value_boolean_array", "value_number_array"):
            value = list(raw.values)
        elif value_field == "value_geo":
            value = {"geoCoordinates": {"latitude": raw.latitude,
                                        "longitude": raw.longitude},
                     "distance": {"max": raw.distance}}
        else:
            value = raw
    return Filter(op, path=path, value=value)


def _struct_to_dict(s) -> dict:
    """google.protobuf.Struct -> dict, MessageToDict-compatible (numbers
    stay floats — Struct is JSON-typed) at ~1/10 the cost; this runs once
    per imported object on the gRPC hot path."""
    out = {}
    for k, v in s.fields.items():
        kind = v.WhichOneof("kind")
        if kind == "string_value":
            out[k] = v.string_value
        elif kind == "number_value":
            out[k] = v.number_value
        elif kind == "bool_value":
            out[k] = v.bool_value
        elif kind == "struct_value":
            out[k] = _struct_to_dict(v.struct_value)
        elif kind == "list_value":
            out[k] = [
                (_struct_to_dict(e.struct_value)
                 if e.WhichOneof("kind") == "struct_value"
                 else json_format.MessageToDict(e))
                for e in v.list_value.values]
        else:  # null_value / unset
            out[k] = None
    return out


def _props_from_batch_object(bo: "pb.BatchObject") -> dict:
    """Flatten the typed batch property payload back into a plain dict
    (the reference re-assembles models.Object the same way,
    v1/batch_parse_request.go). Iterates only the SET fields — walking
    all ten repeated-array fields per object cost ~10 µs each on the
    import hot path."""
    p = bo.properties
    props: dict = {}
    refs: list = []  # applied LAST — pre-rewrite precedence: a prop name
    # set both as a ref and as an array resolves to the ref beacons
    for fd, val in p.ListFields():
        name = fd.name
        if name == "non_ref_properties":
            props.update(_struct_to_dict(val))
        elif name == "number_array_properties":
            for arr in val:
                props[arr.prop_name] = (
                    list(np.frombuffer(arr.values_bytes, dtype="<f8"))
                    if arr.values_bytes else list(arr.values))
        elif name in ("int_array_properties", "text_array_properties",
                      "boolean_array_properties"):
            for arr in val:
                props[arr.prop_name] = list(arr.values)
        elif name == "object_properties":
            for obj in val:
                props[obj.prop_name] = _object_value_to_dict(obj.value)
        elif name == "object_array_properties":
            for arr in val:
                props[arr.prop_name] = [
                    _object_value_to_dict(v) for v in arr.values]
        elif name == "empty_list_props":
            for nm in val:
                props[nm] = []
        elif name == "single_target_ref_props":
            for ref in val:
                refs.append((ref.prop_name, [
                    {"beacon": f"weaviate://localhost/{u}"}
                    for u in ref.uuids]))
        elif name == "multi_target_ref_props":
            for ref in val:
                refs.append((ref.prop_name, [
                    {"beacon":
                     f"weaviate://localhost/{ref.target_collection}/{u}"}
                    for u in ref.uuids]))
    for name, beacons in refs:
        props[name] = beacons
    return props


def _object_value_to_dict(val: "pb.ObjectPropertiesValue") -> dict:
    out = json_format.MessageToDict(val.non_ref_properties)
    for arr in val.number_array_properties:
        out[arr.prop_name] = (
            list(np.frombuffer(arr.values_bytes, dtype="<f8"))
            if arr.values_bytes else list(arr.values))
    for arr in val.int_array_properties:
        out[arr.prop_name] = list(arr.values)
    for arr in val.text_array_properties:
        out[arr.prop_name] = list(arr.values)
    for arr in val.boolean_array_properties:
        out[arr.prop_name] = list(arr.values)
    for obj in val.object_properties:
        out[obj.prop_name] = _object_value_to_dict(obj.value)
    for arr in val.object_array_properties:
        out[arr.prop_name] = [_object_value_to_dict(v) for v in arr.values]
    for name in val.empty_list_props:
        out[name] = []
    return out


# ---------------------------------------------------------------------------
# reply marshalling (reference: v1/prepare_reply.go, mapping.go)
# ---------------------------------------------------------------------------

def _to_value(x, dtype: str | None) -> "pb.Value":
    v = pb.Value()
    if x is None:
        v.null_value = 0
        return v
    if isinstance(x, bool):
        v.bool_value = x
        return v
    if isinstance(x, (int, float, np.integer, np.floating)) \
            and dtype == DataType.INT:
        # Struct-borne numbers are f64; the schema says this one is an int
        v.int_value = int(x)
        return v
    if isinstance(x, (int, float, np.floating, np.integer)):
        if dtype == DataType.DATE:
            v.date_value = str(x)
        else:
            v.number_value = float(x)
        return v
    if isinstance(x, str):
        if dtype == DataType.DATE:
            v.date_value = x
        elif dtype == DataType.UUID:
            v.uuid_value = x
        elif dtype == DataType.BLOB:
            v.blob_value = x
        else:
            v.text_value = x
        return v
    if isinstance(x, dict):
        if "latitude" in x and "longitude" in x:
            v.geo_value.latitude = float(x["latitude"])
            v.geo_value.longitude = float(x["longitude"])
            return v
        for key, sub in x.items():
            v.object_value.fields[key].CopyFrom(_to_value(sub, None))
        return v
    if isinstance(x, (list, tuple, np.ndarray)):
        lv = v.list_value
        seq = list(x)
        if not seq:
            lv.text_values.SetInParent()
        elif all(isinstance(e, bool) for e in seq):
            lv.bool_values.values.extend(seq)
        elif dtype == DataType.INT_ARRAY or all(
                isinstance(e, (int, np.integer)) and not isinstance(e, bool)
                for e in seq):
            lv.int_values.values = np.asarray(seq, dtype="<i8").tobytes()
        elif all(isinstance(e, (int, float, np.floating, np.integer))
                 for e in seq):
            lv.number_values.values = np.asarray(seq, dtype="<f8").tobytes()
        elif dtype == DataType.DATE_ARRAY:
            lv.date_values.values.extend(str(e) for e in seq)
        elif dtype == DataType.UUID_ARRAY:
            lv.uuid_values.values.extend(str(e) for e in seq)
        elif all(isinstance(e, dict) for e in seq):
            for e in seq:
                props = lv.object_values.values.add()
                for key, sub in e.items():
                    props.fields[key].CopyFrom(_to_value(sub, None))
        else:
            lv.text_values.values.extend(str(e) for e in seq)
        return v
    v.text_value = str(x)
    return v


def _f32_bytes(vec) -> bytes:
    return np.asarray(vec, dtype="<f4").tobytes()


class GrpcServer:
    """``db``: node-local Database (or anything exposing get_collection).
    ``modules``: optional module Provider for nearText / generative /
    rerank (usecases/modules analog)."""

    def __init__(self, db, host: str = "127.0.0.1", port: int = 0,
                 modules=None, auth=None, max_workers: int | None = None):
        # 64 workers: handlers mostly BLOCK on the query batcher's device
        # dispatch, so the pool bounds how many queries can coalesce into
        # one batch — 16 capped measured batch sizes at ~8 under 32
        # concurrent streams (GRPC_MAX_WORKERS overrides)
        self.db = db
        self.modules = modules
        self.auth = auth
        if max_workers is None:
            import os

            max_workers = int(os.environ.get("GRPC_MAX_WORKERS", "64"))
        self._max_workers = max_workers
        handlers = {
            "Search": self._search,
            "BatchObjects": self._batch_objects,
            "BatchDelete": self._batch_delete,
            "TenantsGet": self._tenants_get,
        }
        req_types = {
            "Search": pb.SearchRequest,
            "BatchObjects": pb.BatchObjectsRequest,
            "BatchDelete": pb.BatchDeleteRequest,
            "TenantsGet": pb.TenantsGetRequest,
        }
        verbs = {"Search": "read", "TenantsGet": "read",
                 "BatchObjects": "write", "BatchDelete": "write"}
        method_handlers = {}
        for name, fn in handlers.items():
            method_handlers[name] = grpc.unary_unary_rpc_method_handler(
                self._wrap(fn, verbs[name], name),
                request_deserializer=req_types[name].FromString,
                response_serializer=lambda resp: resp.SerializeToString(),
            )
        self._server = grpc.server(ThreadPoolExecutor(max_workers=self._max_workers))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, method_handlers),))
        # grpc.health.v1.Health/Check — the official v4 client health-checks
        # the channel during connect() and refuses the server without it
        # (reference wires grpc-health-probe the same way). The wire format
        # is tiny (HealthCheckResponse{status: SERVING} = 0x08 0x01), so the
        # handler is hand-rolled rather than depending on
        # grpcio-health-checking (not in the image).
        health_handlers = {
            "Check": grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: b"\x08\x01",
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(
                "grpc.health.v1.Health", health_handlers),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host

    def start(self):
        self._server.start()
        return self

    def stop(self, grace: float = 0.5):
        self._server.stop(grace)

    # -- plumbing -----------------------------------------------------------

    @staticmethod
    def _grpc_http_status(code) -> int:
        """gRPC status -> HTTP-ish status for the tailboard's SLO/tail
        accounting (>=500 counts against availability). Client-caused
        codes must land BELOW 500 — UNIMPLEMENTED (nearText without a
        vectorizer module) and FAILED_PRECONDITION (tenant ops on a
        non-MT collection) are request mistakes, not server failures,
        and a stream of them must not page the availability SLO."""
        try:
            return {
                grpc.StatusCode.UNAUTHENTICATED: 401,
                grpc.StatusCode.PERMISSION_DENIED: 403,
                grpc.StatusCode.NOT_FOUND: 404,
                grpc.StatusCode.ALREADY_EXISTS: 409,
                grpc.StatusCode.ABORTED: 409,
                grpc.StatusCode.INVALID_ARGUMENT: 422,
                grpc.StatusCode.OUT_OF_RANGE: 422,
                grpc.StatusCode.FAILED_PRECONDITION: 422,
                grpc.StatusCode.UNIMPLEMENTED: 422,
                grpc.StatusCode.CANCELLED: 499,
                grpc.StatusCode.RESOURCE_EXHAUSTED: 503,
                grpc.StatusCode.UNAVAILABLE: 503,
                grpc.StatusCode.DEADLINE_EXCEEDED: 504,
            }.get(code, 500)
        except TypeError:  # unhashable stub in tests
            return 500

    def _wrap(self, fn, verb: str = "write", rpc_name: str = "rpc"):
        from weaviate_tpu.runtime import tracing

        def handler(request, context):
            # request root trace; clients force device-time sampling by
            # sending an "x-trace: true" metadata key (the gRPC analog
            # of the REST ?trace=true param)
            try:
                md = dict(context.invocation_metadata() or [])
            except Exception:  # noqa: BLE001 — tests stub the context
                md = {}
            force = md.get("x-trace") == "true"
            # "x-explain: true" metadata is the gRPC analog of the REST
            # ?explain=true param: the structured query plan rides back
            # as trailing metadata (protos carry no spare field for it)
            explain = md.get("x-explain") == "true"
            # adopt the client's gRPC deadline as this request's budget:
            # the contextvar propagates it down through the batcher,
            # shard fan-out and every transport call
            from weaviate_tpu.cluster.transport import CircuitOpenError
            from weaviate_tpu.runtime import retry

            budget = None
            expired = False
            try:
                rem = context.time_remaining()
                # no-deadline clients surface as None OR as a huge
                # sentinel (grpc reports ~infinity); adopting that
                # would overflow downstream waits — treat it as "no
                # budget". A deadline that ALREADY elapsed in transit
                # must abort now, not run the full search for a client
                # gRPC has cancelled.
                if rem is not None:
                    if rem <= 0:
                        expired = True
                    elif rem < 86400.0 * 365:
                        budget = rem
            except Exception:  # noqa: BLE001 — tests stub the context
                pass
            if expired:
                context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                              "deadline expired before handling began")
            from weaviate_tpu.runtime import tailboard

            # always-on timeline (tailboard): the rpc name is the
            # operation label; complete() runs BEFORE each abort (abort
            # raises) so the tail keep/drop decision sees the status
            with tailboard.request(f"grpc.{rpc_name.lower()}"):
                try:
                    # auth precedes the trace: rejected clients must not
                    # be able to fill the debug-trace ring
                    self._check_auth(context, verb)
                    from weaviate_tpu.runtime import degrade

                    with tracing.trace(f"grpc.{rpc_name}", force=force), \
                            retry.deadline(budget), degrade.collecting():
                        plan = None
                        if explain:
                            from weaviate_tpu.runtime import kernelscope

                            token = kernelscope.explain_begin()
                            try:
                                reply = fn(request, context)
                            finally:
                                plan = kernelscope.explain_end(token)
                        else:
                            reply = fn(request, context)
                        # a degraded (partial) answer must be visible on
                        # the gRPC surface too: marker list rides
                        # trailing metadata (protos carry no spare field
                        # for it). set_trailing_metadata may only be
                        # called once, so degrade markers and the
                        # explain plan share one call.
                        markers = degrade.snapshot()
                        trailers = []
                        if markers:
                            import json as _json

                            trailers.append(
                                ("x-degraded", _json.dumps(markers)))
                        if plan is not None:
                            import json as _json

                            trailers.append(
                                ("x-explain", _json.dumps(plan)))
                        if trailers:
                            try:
                                context.set_trailing_metadata(
                                    tuple(trailers))
                            except Exception:  # noqa: BLE001 — stubbed ctx
                                pass
                        tailboard.complete(200, degraded=bool(markers))
                        return reply
                except ApiError as e:
                    tailboard.complete(self._grpc_http_status(e.code))
                    context.abort(e.code, e.message)
                except KeyError as e:
                    tailboard.complete(404)
                    context.abort(grpc.StatusCode.NOT_FOUND, str(e))
                except ValueError as e:
                    tailboard.complete(422)
                    context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                except retry.DeadlineExceeded as e:
                    # typed: budget ran out mid-flight — not INTERNAL
                    tailboard.complete(504)
                    context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                                  str(e))
                except (retry.OverloadedError, CircuitOpenError) as e:
                    # retriable overload / open breaker: clients back off
                    tailboard.complete(503)
                    context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
                except Exception as e:  # noqa: BLE001 — INTERNAL
                    logger.exception("grpc handler failed")
                    tailboard.complete(500)
                    context.abort(grpc.StatusCode.INTERNAL, str(e))
        return handler

    def _check_auth(self, context, verb: str):
        """auth interceptor analog (reference: grpc/server.go auth
        interceptor reads the authorization metadata key)."""
        if self.auth is None:
            return
        from weaviate_tpu.auth import AuthError, ForbiddenError

        md = dict(context.invocation_metadata() or [])
        try:
            self.auth.check(md.get("authorization") or None, verb)
        except AuthError as e:
            raise ApiError(grpc.StatusCode.UNAUTHENTICATED, str(e))
        except ForbiddenError as e:
            raise ApiError(grpc.StatusCode.PERMISSION_DENIED, str(e))

    def _collection(self, name: str):
        return self.db.get_collection(name)

    # -- Search (service.go:173) --------------------------------------------

    def _search(self, req: "pb.SearchRequest", context) -> "pb.SearchReply":
        start = time.perf_counter()
        col = self._collection(req.collection)
        tenant = req.tenant or None
        # identity for the always-on phase histograms (tailboard top-K
        # guard clamps the label values)
        from weaviate_tpu.runtime import tailboard

        tailboard.annotate(collection=req.collection, tenant=tenant)
        limit = req.limit or 10
        where = filters_from_pb(req.filters) if req.HasField("filters") else None
        autocut = req.autocut

        search_kind = None
        for field in ("near_vector", "near_object", "near_text", "bm25_search",
                      "hybrid_search", "near_image", "near_audio", "near_video",
                      "near_depth", "near_thermal", "near_imu"):
            if req.HasField(field):
                search_kind = field
                break

        results = None
        fetched_objects = None
        if search_kind == "near_vector":
            nv = req.near_vector
            vec = _vector_from(nv.vector_bytes, nv.vector)
            if vec is None:
                raise ApiError(grpc.StatusCode.INVALID_ARGUMENT,
                               "nearVector requires a vector")
            max_dist = nv.distance if nv.HasField("distance") else (
                2 * (1 - nv.certainty) if nv.HasField("certainty") else None)
            vec_name = nv.target_vectors[0] if nv.target_vectors else ""
            results = col.near_vector(
                vec, k=limit + req.offset, vec_name=vec_name, tenant=tenant,
                where=where, max_distance=max_dist, autocut=autocut)
        elif search_kind == "near_object":
            no = req.near_object
            anchor = col.get_object(no.id, tenant=tenant)
            if anchor is None:
                raise ApiError(grpc.StatusCode.NOT_FOUND,
                               f"nearObject id {no.id} not found")
            vec_name = no.target_vectors[0] if no.target_vectors else ""
            vec = anchor.vectors.get(vec_name)
            if vec is None:
                raise ApiError(grpc.StatusCode.INVALID_ARGUMENT,
                               f"anchor object has no vector {vec_name!r}")
            max_dist = no.distance if no.HasField("distance") else None
            results = col.near_vector(
                vec, k=limit + req.offset, vec_name=vec_name, tenant=tenant,
                where=where, max_distance=max_dist, autocut=autocut)
        elif search_kind == "near_text":
            nt = req.near_text
            vec_name = nt.target_vectors[0] if nt.target_vectors else ""
            vec = self._vectorize_query(col, " ".join(nt.query), nt, vec_name)
            max_dist = nt.distance if nt.HasField("distance") else (
                2 * (1 - nt.certainty) if nt.HasField("certainty") else None)
            results = col.near_vector(
                vec, k=limit + req.offset, vec_name=vec_name, tenant=tenant,
                where=where, max_distance=max_dist, autocut=autocut)
        elif search_kind == "bm25_search":
            results = col.bm25(req.bm25_search.query, k=limit + req.offset,
                               properties=list(req.bm25_search.properties) or None,
                               tenant=tenant, where=where, autocut=autocut)
        elif search_kind == "hybrid_search":
            h = req.hybrid_search
            vec = _vector_from(h.vector_bytes, h.vector)
            vec_name = h.target_vectors[0] if h.target_vectors else ""
            if vec is None and h.HasField("near_vector"):
                vec = _vector_from(h.near_vector.vector_bytes,
                                   h.near_vector.vector)
                # a vector riding in near_vector may name its target
                # there instead of on the Hybrid message
                if not vec_name and h.near_vector.target_vectors:
                    vec_name = h.near_vector.target_vectors[0]
            if vec is None and (h.HasField("near_text") or h.query) \
                    and self._has_vectorizer(col, vec_name):
                nt = h.near_text if h.HasField("near_text") else None
                text = " ".join(nt.query) if nt is not None else h.query
                vec = self._vectorize_query(col, text, nt, vec_name)
            fusion = "rankedFusion" \
                if h.fusion_type == pb.Hybrid.FUSION_TYPE_RANKED \
                else "relativeScore"
            # honor alpha verbatim — clients always send it, and proto3
            # cannot distinguish an explicit 0 (pure BM25) from unset
            results = col.hybrid(h.query, vector=vec, alpha=h.alpha,
                                 k=limit + req.offset,
                                 properties=list(h.properties) or None,
                                 vec_name=vec_name, tenant=tenant,
                                 fusion=fusion, where=where, autocut=autocut)
        elif search_kind is not None:
            results = self._near_media(col, req, search_kind, limit, tenant,
                                       where, autocut)
        else:
            sort = [{"path": list(s.path), "order":
                     "asc" if s.ascending else "desc"} for s in req.sort_by]
            fetched_objects = col.fetch_objects(
                limit=limit, offset=req.offset, sort=sort or None,
                where=where, tenant=tenant, after=req.after or None)

        if results is not None and req.offset:
            results = results[req.offset:]
        if results is not None:
            results = results[:limit]

        reply = pb.SearchReply()
        meta_req = req.metadata if req.HasField("metadata") else None
        props_req = req.properties if req.HasField("properties") else None
        # pre-1.23 clients set neither api flag and read the deprecated
        # Struct field (search_get.proto:272); modern clients
        # (uses_123_api / uses_125_api) read the typed non_ref_props
        legacy_props = not (req.uses_123_api or req.uses_125_api)
        generative = req.generative if req.HasField("generative") else None
        rerank = req.rerank if req.HasField("rerank") else None

        if results is not None and rerank is not None:
            results = self._rerank(col, results, rerank)

        dtype_of = {p.name: p.data_type for p in col.config.properties}
        if results is not None and req.HasField("group_by"):
            self._group_results(col, reply, results, req.group_by,
                                meta_req, props_req, dtype_of)
        elif results is not None:
            for r in results:
                if r.object is None:
                    continue
                out = reply.results.add()
                self._fill_result(col, out, r.object, r, meta_req, props_req,
                                  dtype_of, legacy_props=legacy_props)
        else:
            for obj in fetched_objects:
                out = reply.results.add()
                self._fill_result(col, out, obj, None, meta_req, props_req,
                                  dtype_of, legacy_props=legacy_props)

        if generative is not None:
            self._generate(col, reply, generative)

        reply.took = time.perf_counter() - start
        return reply

    # -- module hooks (filled in by the module provider when attached) -------

    def _has_vectorizer(self, col, vec_name: str = "") -> bool:
        if self.modules is None:
            return False
        try:
            return self.modules.vectorizer_for(col.config, vec_name) is not None
        except Exception:  # configured module not registered -> BM25 fallback
            return False

    def _vectorize_query(self, col, text: str, near_text,
                         vec_name: str = "") -> np.ndarray:
        if self.modules is None:
            raise ApiError(grpc.StatusCode.UNIMPLEMENTED,
                           "nearText requires a vectorizer module")
        vec = self.modules.vectorize_query(col.config, text, vec_name)
        if near_text is not None:
            vec = self.modules.apply_moves(col, vec, near_text, vec_name)
        return vec

    def _near_media(self, col, req, kind, limit, tenant, where, autocut):
        if self.modules is None:
            raise ApiError(grpc.StatusCode.UNIMPLEMENTED,
                           f"{kind} requires a multi2vec module")
        msg = getattr(req, kind)
        media = getattr(msg, kind.replace("near_", ""))
        vec_name = msg.target_vectors[0] if msg.target_vectors else ""
        vec = self.modules.vectorize_media(
            col.config, kind.replace("near_", ""), media, vec_name)
        max_dist = msg.distance if msg.HasField("distance") else None
        return col.near_vector(vec, k=limit + req.offset, vec_name=vec_name,
                               tenant=tenant, where=where,
                               max_distance=max_dist, autocut=autocut)

    def _rerank(self, col, results, rerank):
        if self.modules is None:
            raise ApiError(grpc.StatusCode.UNIMPLEMENTED,
                           "rerank requires a reranker module")
        docs = [str((r.object.properties if r.object else {}).get(
            rerank.property, "")) for r in results]
        scores = self.modules.rerank(col.config, rerank.query or "", docs)
        for r, s in zip(results, scores):
            r.rerank_score = s
        results.sort(key=lambda r: -(r.rerank_score or 0.0))
        return results

    def _generate(self, col, reply, generative):
        if self.modules is None:
            raise ApiError(grpc.StatusCode.UNIMPLEMENTED,
                           "generative search requires a generative module")
        outs = list(reply.results) or [o for g in reply.group_by_results
                                       for o in g.objects]
        if generative.single_response_prompt:
            for out in outs:
                props = json_format.MessageToDict(
                    out.properties.non_ref_properties)
                props.update({k: _value_to_py(v) for k, v in
                              out.properties.non_ref_props.fields.items()})
                text = self.modules.generate_single(
                    col.config, generative.single_response_prompt, props)
                out.metadata.generative = text
                out.metadata.generative_present = True
        if generative.grouped_response_task:
            all_props = []
            for out in outs:
                props = {k: _value_to_py(v) for k, v in
                         out.properties.non_ref_props.fields.items()}
                if generative.grouped_properties:
                    props = {k: v for k, v in props.items()
                             if k in generative.grouped_properties}
                all_props.append(props)
            reply.generative_grouped_result = self.modules.generate_grouped(
                col.config, generative.grouped_response_task, all_props)

    # -- result marshalling --------------------------------------------------

    def _fill_result(self, col, out: "pb.SearchResult", obj, res,
                     meta_req, props_req, dtype_of=None,
                     legacy_props=False):
        md = out.metadata
        if meta_req is None or meta_req.uuid:
            md.id = obj.uuid
        if meta_req is not None:
            if meta_req.vector and obj.vector is not None:
                md.vector_bytes = _f32_bytes(obj.vector)
            for name in meta_req.vectors:
                if name in obj.vectors:
                    v = md.vectors.add()
                    v.name = name
                    v.vector_bytes = _f32_bytes(obj.vectors[name])
            if meta_req.creation_time_unix:
                md.creation_time_unix = obj.creation_time_ms
                md.creation_time_unix_present = True
            if meta_req.last_update_time_unix:
                md.last_update_time_unix = obj.last_update_time_ms
                md.last_update_time_unix_present = True
            if res is not None and res.distance is not None:
                if meta_req.distance:
                    md.distance = res.distance
                    md.distance_present = True
                if meta_req.certainty:
                    md.certainty = max(0.0, 1.0 - res.distance / 2.0)
                    md.certainty_present = True
            if res is not None and meta_req.score and res.score is not None:
                md.score = res.score
                md.score_present = True
        # rerank score rides along whenever a reranker ran, like the
        # reference's _additional{rerank} — not gated on MetadataRequest
        rr = getattr(res, "rerank_score", None) if res is not None else None
        if rr is not None:
            md.rerank_score = rr
            md.rerank_score_present = True
        props = out.properties
        if dtype_of is None:
            dtype_of = {p.name: p.data_type for p in col.config.properties}
        requested = None
        if props_req is not None and not props_req.return_all_nonref_properties:
            requested = set(props_req.non_ref_properties) or None
        for key, val in obj.properties.items():
            if requested is not None and key not in requested:
                continue
            dtype = dtype_of.get(key)
            if dtype == DataType.REFERENCE:
                continue
            props.non_ref_props.fields[key].CopyFrom(_to_value(val, dtype))
            if legacy_props and dtype != DataType.GEO:
                try:
                    # Struct.update merges key-by-key (ParseDict would
                    # clear previously-written keys)
                    props.non_ref_properties.update({key: val})
                except Exception:  # noqa: BLE001 - non-Struct-able value
                    pass
        props.target_collection = col.config.name

    def _group_results(self, col, reply, results, group_by,
                       meta_req, props_req, dtype_of=None):
        """Group hits by a property value (reference: GroupBy over one
        path entry, prepare_reply.go groupByResults)."""
        path = list(group_by.path)
        prop = path[0] if path else ""
        groups: dict[str, list] = {}
        order: list[str] = []
        for r in results:
            if r.object is None:
                continue
            key = str(r.object.properties.get(prop))
            if key not in groups:
                if group_by.number_of_groups and \
                        len(order) >= group_by.number_of_groups:
                    continue
                groups[key] = []
                order.append(key)
            if group_by.objects_per_group and \
                    len(groups[key]) >= group_by.objects_per_group:
                continue
            groups[key].append(r)
        for key in order:
            members = groups[key]
            g = reply.group_by_results.add()
            g.name = key
            dists = [m.distance for m in members if m.distance is not None]
            if dists:
                g.min_distance = min(dists)
                g.max_distance = max(dists)
            g.number_of_objects = len(members)
            for m in members:
                out = g.objects.add()
                self._fill_result(col, out, m.object, m, meta_req, props_req,
                                  dtype_of)

    # -- BatchObjects (service.go:126) ---------------------------------------

    def _batch_objects(self, req: "pb.BatchObjectsRequest",
                       context) -> "pb.BatchObjectsReply":
        start = time.perf_counter()
        consistency = _CONSISTENCY[req.consistency_level] \
            if req.HasField("consistency_level") else "QUORUM"
        by_target: dict[tuple[str, str], list[tuple[int, "pb.BatchObject"]]] = {}
        for i, bo in enumerate(req.objects):
            by_target.setdefault((bo.collection, bo.tenant), []).append((i, bo))
        reply = pb.BatchObjectsReply()
        for (cname, tenant), entries in by_target.items():
            try:
                col = self._collection(cname)
            except KeyError as e:
                for i, _bo in entries:
                    err = reply.errors.add()
                    err.index = i
                    err.error = str(e)
                continue
            specs = []
            for _i, bo in entries:
                spec = {"uuid": bo.uuid or None,
                        "properties": _props_from_batch_object(bo)}
                vec = _vector_from(bo.vector_bytes, bo.vector)
                if vec is not None:
                    spec["vector"] = vec
                named = {}
                for v in bo.vectors:
                    named[v.name] = np.frombuffer(
                        v.vector_bytes, dtype="<f4").astype(np.float32)
                if named:
                    spec["vectors"] = named
                specs.append(spec)
            if self.modules is not None:
                try:
                    self.modules.vectorize_batch(col.config, specs)
                except Exception as e:  # per-object errors, not whole-batch
                    from weaviate_tpu.modules.provider import needs_vector

                    kept = []
                    for (i, _bo), spec in zip(entries, specs):
                        if needs_vector(col.config, spec):
                            err = reply.errors.add()
                            err.index = i
                            err.error = f"vectorize: {e}"
                        else:
                            kept.append(((i, _bo), spec))
                    entries = [ent for ent, _s in kept]
                    specs = [s for _ent, s in kept]
            outcomes = col.batch_put(specs, tenant=tenant or None,
                                     consistency=consistency)
            for (i, _bo), out in zip(entries, outcomes):
                if out["status"] != "SUCCESS":
                    err = reply.errors.add()
                    err.index = i
                    err.error = out.get("error", "")
        reply.took = time.perf_counter() - start
        return reply

    # -- BatchDelete ---------------------------------------------------------

    def _batch_delete(self, req: "pb.BatchDeleteRequest",
                      context) -> "pb.BatchDeleteReply":
        start = time.perf_counter()
        col = self._collection(req.collection)
        if not req.HasField("filters"):
            # a filterless batch delete would wipe the collection; the
            # reference requires match.where (usecases/objects validation)
            raise ApiError(grpc.StatusCode.INVALID_ARGUMENT,
                           "batch delete requires a where filter")
        where = filters_from_pb(req.filters)
        consistency = _CONSISTENCY[req.consistency_level] \
            if req.HasField("consistency_level") else "QUORUM"
        result = col.batch_delete(
            where, tenant=req.tenant or None, dry_run=req.dry_run,
            verbose=req.verbose, consistency=consistency)
        reply = pb.BatchDeleteReply(
            matches=result["matches"], successful=result["successful"],
            failed=result["failed"])
        for entry in result["objects"]:
            obj = reply.objects.add()
            try:  # clients expect raw UUID bytes (batch_delete.proto uuid)
                obj.uuid = _uuid.UUID(entry["id"]).bytes
            except ValueError:
                obj.uuid = entry["id"].encode()
            obj.successful = entry["successful"]
            if entry.get("error"):
                obj.error = entry["error"]
        reply.took = time.perf_counter() - start
        return reply

    # -- TenantsGet ----------------------------------------------------------

    def _tenants_get(self, req: "pb.TenantsGetRequest",
                     context) -> "pb.TenantsGetReply":
        start = time.perf_counter()
        col = self._collection(req.collection)
        if not col.config.multi_tenancy.enabled:
            raise ApiError(grpc.StatusCode.FAILED_PRECONDITION,
                           "multi-tenancy is not enabled")
        names = col.tenants()
        if req.HasField("names"):
            wanted = set(req.names.values)
            names = [n for n in names if n in wanted]
        reply = pb.TenantsGetReply()
        for n in sorted(names):
            t = reply.tenants.add()
            t.name = n
            t.activity_status = pb.TENANT_ACTIVITY_STATUS_HOT
        reply.took = time.perf_counter() - start
        return reply


def _value_to_py(v: "pb.Value"):
    kind = v.WhichOneof("kind")
    if kind is None or kind == "null_value":
        return None
    raw = getattr(v, kind)
    if kind == "list_value":
        lk = raw.WhichOneof("kind")
        if lk == "number_values":
            return list(np.frombuffer(raw.number_values.values, dtype="<f8"))
        if lk == "int_values":
            return list(np.frombuffer(raw.int_values.values, dtype="<i8"))
        if lk is not None:
            return list(getattr(raw, lk).values)
        return [_value_to_py(e) for e in raw.values]
    if kind == "object_value":
        return {k: _value_to_py(sub) for k, sub in raw.fields.items()}
    if kind == "geo_value":
        return {"latitude": raw.latitude, "longitude": raw.longitude}
    return raw
