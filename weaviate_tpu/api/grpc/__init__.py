"""gRPC v1 API (reference: adapters/handlers/grpc/ + grpc/proto/v1).

Wire-compatible with reference v1 clients: same package, messages, field
numbers (see v1.proto). The servicer is hand-wired through
``grpc.method_handlers_generic_handler`` instead of grpc_tools-generated
stubs (grpc_tools is not in this environment; the generated wiring is the
same four unary-unary handlers).
"""

from weaviate_tpu.api.grpc.server import GrpcServer

__all__ = ["GrpcServer"]
