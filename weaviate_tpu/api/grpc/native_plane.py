"""Native gRPC data plane: Python side.

Pairs csrc/dataplane.cpp (epoll + libnghttp2 transport, fast-path
Search parse, batch coalescing, C++ reply building) with this dispatcher:

- search batches -> ONE Shard device dispatch for the whole coalesced
  batch. The dispatch loop is PIPELINED (ISSUE 7): it launches batch N
  via ``Shard.vector_search_batch_async`` (device-resident
  DeviceResultHandle) and hands the handle to a transfer thread, then
  immediately waits for batch N+1 — while N's results drain D2H, N+1's
  program is already on the device. Results go back via dp_post_batch,
  which builds every reply in C++ from the docid -> (uuid,
  PropertiesResult bytes) cache. Cache misses come back here, get
  answered through ONE batched LSM read (``Shard.objects_by_doc_ids``
  -> ``kv.get_many``) that also seeds the cache — the plane self-warms,
  no import hook needed (docids are never reused, so entries can't go
  stale). The warm pass reads through the same batched LSM feed.
- everything else (filters, hybrid, tenants, BatchObjects, ...) arrives
  as raw request bytes and is answered by the SAME servicer methods the
  Python gRPC server uses (GrpcServer handlers), so behavior is
  identical by construction.

Reference bar: Go handlers scaling with cores
(adapters/handlers/grpc/server.go:50, adapters/repos/db/index.go:1576).
Enable with WEAVIATE_TPU_NATIVE_DATAPLANE=1 (requires libnghttp2 and no
auth configured — fallback requests carry no per-request credentials).
"""

from __future__ import annotations

import logging
import threading
import time

import grpc
import numpy as np

from weaviate_tpu.api.grpc import v1_pb2 as pb
from weaviate_tpu.native import dataplane as dpn
from weaviate_tpu.runtime import degrade, tailboard
from weaviate_tpu.runtime.transfer import TransferPipeline

logger = logging.getLogger(__name__)

_REQ_TYPES = {
    "Search": pb.SearchRequest,
    "BatchObjects": pb.BatchObjectsRequest,
    "BatchDelete": pb.BatchDeleteRequest,
    "TenantsGet": pb.TenantsGetRequest,
}


class _Ctx:
    """Minimal grpc.ServicerContext stand-in for fallback dispatch."""

    class Abort(Exception):
        def __init__(self, code, message):
            self.code = code
            self.message = message

    def invocation_metadata(self):
        return []

    def abort(self, code, message):
        raise _Ctx.Abort(code, message)


class NativeDataPlane:
    """Drop-in for GrpcServer (same ``port``/``start``/``stop`` surface),
    serving the gRPC port through the C++ transport."""

    def __init__(self, db, grpc_server, host: str = "127.0.0.1",
                 port: int = 0, window_us: int = 0):
        self.db = db
        self.server = grpc_server  # handler logic donor (not started)
        self.dp = dpn.DataPlane(port=port, window_us=window_us)
        self.port = self.dp.port
        self.host = host
        self._coll_by_id: dict[int, str] = {}
        self._registered: set[str] = set()
        self._reg_lock = threading.Lock()  # dispatch vs warm threads
        self._warm_threads: dict[str, threading.Thread] = {}
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # double-buffered D2H drain for the pipelined dispatch loop:
        # depth 2 = batch N draining + batch N+1 dispatched; the
        # dispatcher blocks before launching N+2 (backpressure)
        self._transfer = TransferPipeline(depth=2, name="dp-transfer")

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        t = threading.Thread(target=self._dispatch_loop,
                             name="dp-dispatch", daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self, grace: float = 0.5):
        self._stop.set()
        # drain in-flight transfers FIRST so queued replies still post
        # through the live C++ plane, then stop it
        self._transfer.stop(timeout=grace + 1.0)
        self.dp.stop()
        for t in self._threads:
            t.join(timeout=grace + 1.0)

    # -- collection registry --------------------------------------------------

    def _eligible(self, col) -> bool:
        """Fast-path only for the plain shape: single shard, single
        tenant, unreplicated, default vector. Everything else still
        works — through the fallback."""
        cfg = col.config
        if cfg.multi_tenancy.enabled:
            return False
        if getattr(cfg.replication, "factor", 1) > 1:
            return False
        if len(col.shards) != 1:
            return False
        return True

    def _maybe_register(self, name: str, warm: bool = True):
        if name in self._registered:
            return
        try:
            col = self.db.get_collection(name)
        except Exception:
            return
        if not self._eligible(col):
            with self._reg_lock:
                self._registered.add(name)  # don't re-check every query
            return
        shard = next(iter(col.shards.values()))
        idx = shard.vector_indexes.get("")
        if idx is None or not hasattr(idx, "search_by_vector_batch"):
            return  # not ready yet (no vectors imported)
        cid = self.dp.register_collection(name, int(idx.dim))
        if cid >= 0:
            with self._reg_lock:
                self._coll_by_id[cid] = name
                self._registered.add(name)
            if warm:
                # bulk-warm the reply cache off the dispatch thread;
                # misses self-seed in the meantime. Started UNDER the
                # lock so warm_collection() can never observe (and
                # join) a published-but-unstarted thread; the warm
                # thread itself re-takes the lock only after start.
                t = threading.Thread(target=self._warm_once, args=(name,),
                                     name=f"dp-warm-{name}", daemon=True)
                with self._reg_lock:
                    self._warm_threads[name] = t
                    t.start()

    def wait_registered(self, name: str, timeout: float = 10.0) -> bool:
        """Block until `name` is fast-path registered (registration runs
        on the dispatcher thread after the first Search on it)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._reg_lock:
                if name in self._coll_by_id.values():
                    return True
            time.sleep(0.02)
        return False

    def warm_collection(self, name: str, chunk: int = 2048) -> bool:
        """Ensure the reply cache for `name` is fully warm. Joins an
        in-flight auto-warm instead of repeating the O(corpus) pass;
        returns False when the collection never registered."""
        with self._reg_lock:
            t = self._warm_threads.get(name)
        if t is not None:
            t.join()
            return True
        return self._warm_once(name, chunk)

    def _warm_once(self, name: str, chunk: int = 2048) -> bool:
        """One O(corpus) pass populating the C++ docid -> (uuid,
        PropertiesResult) reply cache; after it, plain nearVector
        queries never touch Python per-query. Objects come out of the
        LSM side in ``chunk``-sized ``kv.get_many`` batches (one layer
        snapshot per chunk) instead of a point lookup per doc."""
        cid = None
        with self._reg_lock:
            items = list(self._coll_by_id.items())
        for c, n in items:
            if n == name:
                cid = c
        if cid is None:
            return False
        col = self.db.get_collection(name)
        shard = next(iter(col.shards.values()))
        dtype_of = {p.name: p.data_type for p in col.config.properties}
        all_docs = list(shard._doc_to_uuid.keys())
        for s in range(0, len(all_docs), chunk):
            doc_chunk = all_docs[s:s + chunk]
            ids: list[int] = []
            uuids: list[str] = []
            props: list[bytes] = []
            for doc_id, obj in zip(doc_chunk,
                                   shard.objects_by_doc_ids(doc_chunk)):
                if obj is None:
                    continue
                out = pb.SearchResult()
                self.server._fill_result(col, out, obj, None, _FAST_META,
                                         None, dtype_of)
                ids.append(doc_id)
                uuids.append(obj.uuid)
                props.append(out.properties.SerializeToString())
            if ids:
                self.dp.cache_put(cid, ids, uuids, props)
        return True

    # -- dispatch -------------------------------------------------------------

    def _dispatch_loop(self):
        while not self._stop.is_set():
            try:
                item = self.dp.wait(200)
            except Exception:
                if self._stop.is_set():
                    return
                raise
            if item is None:
                continue
            if item == "stopped":
                return
            try:
                if isinstance(item, dpn.SearchBatch):
                    self._run_batch(item)
                else:
                    self._run_fallback(item)
            except Exception:  # noqa: BLE001 — keep serving
                logger.exception("data plane dispatch failed")
                # every stream in the failed item must get an error reply
                # or its client hangs until the deadline
                toks = (item.tokens.tolist()
                        if isinstance(item, dpn.SearchBatch)
                        else [item.token])
                for tok in toks:
                    try:
                        self.dp.post_raw(int(tok), b"", 13, "internal error")
                    except Exception:
                        pass

    def _run_batch(self, batch: dpn.SearchBatch):
        t0 = time.perf_counter()
        name = self._coll_by_id.get(batch.coll_id)
        col = self.db.get_collection(name)
        shard = next(iter(col.shards.values()))
        kmax = int(batch.ks.max())
        # pipelined path: dispatch-and-go — the handle drains on the
        # transfer thread while this loop returns to dp.wait() and
        # launches the NEXT batch's program
        handle = shard.vector_search_batch_async(batch.queries, kmax)
        if handle is None:
            ids, dists, counts = shard.vector_search_batch(
                batch.queries, kmax)
            self._finish_batch(batch, col, shard, ids, dists, counts,
                               time.perf_counter() - t0)
            return

        def _fail_batch(_batch):
            for tok in _batch.tokens.tolist():
                try:
                    self.dp.post_raw(int(tok), b"", 13, "internal error")
                except Exception:  # noqa: BLE001
                    pass

        def _serve(res, _batch, _col, _shard, _t0):
            ids, dists, counts = res
            try:
                self._finish_batch(_batch, _col, _shard, ids, dists,
                                   counts, time.perf_counter() - _t0)
                if degrade.is_unhealthy("native_plane"):
                    degrade.mark_healthy("native_plane")
            except Exception:  # noqa: BLE001 — clients must not hang
                logger.exception("pipelined reply build failed")
                _fail_batch(_batch)

        def _done(res, err, _t_fetch0, _t_fetch1, _batch=batch, _col=col,
                  _shard=shard, _t0=t0):
            if err is None:
                _serve(res, _batch, _col, _shard, _t0)
                return
            # faulted device batch: retry ONCE through the sync path
            # (queries are still host-resident), then error only THIS
            # batch's waiters and flip the plane's unhealthy flag —
            # visible in /v1/nodes until a batch serves again. The
            # retry is a full device dispatch, so it leaves the
            # transfer thread: blocking here would stall every other
            # in-flight batch's D2H behind one faulted batch.
            logger.warning("pipelined batch faulted; retrying once "
                           "synchronously: %s", err)
            from weaviate_tpu.runtime.metrics import (
                native_dispatch_retries)

            native_dispatch_retries.inc()

            def _retry_path():
                try:
                    res2 = _shard.vector_search_batch(
                        _batch.queries, int(_batch.ks.max()))
                except Exception as e2:  # noqa: BLE001
                    logger.error("pipelined batch failed after retry",
                                 exc_info=e2)
                    degrade.mark_unhealthy(
                        "native_plane",
                        f"batch dispatch failed twice: {err}; "
                        f"retry: {e2}")
                    _fail_batch(_batch)
                    return
                _serve(res2, _batch, _col, _shard, _t0)

            threading.Thread(target=_retry_path, daemon=True,
                             name="native-plane-fault-retry").start()

        self._transfer.submit(handle, _done)

    def _finish_batch(self, batch: dpn.SearchBatch, col, shard, ids,
                      dists, counts, took: float):
        """Host half of a coalesced Search batch: post to the C++ reply
        builder; answer its cache misses from ONE batched LSM read
        (``objects_by_doc_ids`` -> ``kv.get_many``) and seed the cache
        so the next occurrence of those docs never leaves C++."""
        miss = self.dp.post_batch(batch, ids, dists, counts, took)
        # flight-recorder record for the native plane's dispatch loop —
        # the C++ fast path has no per-request Python, so per-BATCH
        # records are its only always-on attribution
        tailboard.record_dispatch(
            "native", batch=int(len(batch.tokens)),
            k=int(batch.ks.max()) if len(batch.ks) else 0,
            took_ms=round(took * 1000.0, 3), cache_misses=int(len(miss)),
            window_inflight=self._transfer.inflight)
        if len(miss) == 0:
            return
        tok_pos = {int(t): i for i, t in enumerate(batch.tokens)}
        # one get_many for every doc the missed replies need, deduped
        need: list[int] = []
        seen: set[int] = set()
        for t in miss:
            i = tok_pos[int(t)]
            n = int(min(counts[i], batch.ks[i]))
            for j in range(n):
                doc = int(ids[i, j])
                if doc >= 0 and doc not in seen:
                    seen.add(doc)
                    need.append(doc)
        objs = dict(zip(need, shard.objects_by_doc_ids(need)))
        seed_ids: list[int] = []
        seed_uuids: list[str] = []
        seed_props: list[bytes] = []
        dtype_of = {p.name: p.data_type for p in col.config.properties}
        for t in miss:
            i = tok_pos[int(t)]
            reply = pb.SearchReply(took=took)
            n = int(min(counts[i], batch.ks[i]))
            for j in range(n):
                doc = int(ids[i, j])
                obj = objs.get(doc)
                if obj is None:
                    continue
                out = reply.results.add()
                res = _Res(float(dists[i, j]))
                self.server._fill_result(col, out, obj, res,
                                         _FAST_META, None, dtype_of)
                seed_ids.append(doc)
                seed_uuids.append(obj.uuid)
                seed_props.append(out.properties.SerializeToString())
            self.dp.post_raw(int(t), reply.SerializeToString())
        if seed_ids:
            self.dp.cache_put(batch.coll_id, seed_ids, seed_uuids,
                              seed_props)

    def _run_fallback(self, item: dpn.FallbackRequest):
        method = item.method.rsplit("/", 1)[-1]
        handler = {
            "Search": self.server._search,
            "BatchObjects": self.server._batch_objects,
            "BatchDelete": self.server._batch_delete,
            "TenantsGet": self.server._tenants_get,
        }.get(method)
        if handler is None:
            self.dp.post_raw(item.token, b"", 12,
                             f"unknown method {item.method}")
            return
        from weaviate_tpu.api.grpc.server import ApiError

        req_type = _REQ_TYPES[method]
        ctx = _Ctx()
        # fallback requests bypass GrpcServer._wrap, so they open their
        # own always-on timeline (the fast path is per-batch C++ and is
        # covered by the flight recorder instead)
        with tailboard.request(f"grpc.{method.lower()}"):
            try:
                req = req_type.FromString(item.payload)
                reply = handler(req, ctx)
                tailboard.complete(200)
                self.dp.post_raw(item.token, reply.SerializeToString())
                # a Search that fell back on an unregistered collection
                # registers it so the NEXT plain query takes the fast path
                if method == "Search" and req.collection:
                    self._maybe_register(req.collection)
            except (_Ctx.Abort, ApiError) as e:
                code = e.code.value[0] if hasattr(e.code, "value") \
                    else int(e.code)
                # same gRPC->HTTP-ish mapping as the wrapped edge, so
                # UNAVAILABLE/DEADLINE failures count against the SLO
                # here too instead of masquerading as client errors
                from weaviate_tpu.api.grpc.server import GrpcServer

                tailboard.complete(GrpcServer._grpc_http_status(e.code))
                self.dp.post_raw(item.token, b"", code, str(e.message))
            except KeyError as e:
                tailboard.complete(404)
                self.dp.post_raw(item.token, b"",
                                 grpc.StatusCode.NOT_FOUND.value[0], str(e))
            except ValueError as e:
                tailboard.complete(422)
                self.dp.post_raw(
                    item.token, b"",
                    grpc.StatusCode.INVALID_ARGUMENT.value[0], str(e))
            except Exception as e:  # noqa: BLE001
                logger.exception("fallback handler failed")
                tailboard.complete(500)
                self.dp.post_raw(item.token, b"",
                                 grpc.StatusCode.INTERNAL.value[0], str(e))


class _Res:
    """SearchResult stand-in for _fill_result on the fast path."""

    __slots__ = ("distance", "score", "rerank_score")

    def __init__(self, distance: float):
        self.distance = distance
        self.score = None
        self.rerank_score = None


_FAST_META = pb.MetadataRequest(uuid=True, distance=True)
