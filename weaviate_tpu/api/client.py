"""Minimal Python client for the REST API.

Reference: the generated go-swagger client (client/, 34k lines) used by
the acceptance tests — this is the hand-rolled equivalent for ours.
"""

from __future__ import annotations

import http.client
import json
import urllib.parse


class RestError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"{status}: {message}")
        self.status = status


class Client:
    def __init__(self, addr: str, timeout: float = 30.0):
        self.addr = addr
        self.timeout = timeout

    def request(self, method: str, path: str, params: dict | None = None,
                body=None):
        host, _, port = self.addr.partition(":")
        if params:
            path = path + "?" + urllib.parse.urlencode(
                {k: v for k, v in params.items() if v is not None})
        conn = http.client.HTTPConnection(host, int(port),
                                          timeout=self.timeout)
        try:
            conn.request(method, path,
                         body=None if body is None else json.dumps(body),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            raw = resp.read()
        finally:
            conn.close()
        payload = json.loads(raw) if raw else None
        if resp.status >= 400:
            msg = ""
            if isinstance(payload, dict) and payload.get("error"):
                msg = payload["error"][0].get("message", "")
            raise RestError(resp.status, msg)
        return payload

    # -- meta -----------------------------------------------------------------

    def meta(self) -> dict:
        return self.request("GET", "/v1/meta")

    def ready(self) -> bool:
        try:
            self.request("GET", "/.well-known/ready")
            return True
        except (RestError, OSError):
            return False

    def nodes(self) -> list[dict]:
        return self.request("GET", "/v1/nodes")["nodes"]

    # -- schema ---------------------------------------------------------------

    def create_class(self, config: dict) -> dict:
        return self.request("POST", "/v1/schema", body=config)

    def get_schema(self) -> dict:
        return self.request("GET", "/v1/schema")

    def get_class(self, name: str) -> dict:
        return self.request("GET", f"/v1/schema/{name}")

    def delete_class(self, name: str) -> None:
        self.request("DELETE", f"/v1/schema/{name}")

    def add_property(self, class_name: str, prop: dict) -> dict:
        return self.request("POST", f"/v1/schema/{class_name}/properties",
                            body=prop)

    def add_tenants(self, class_name: str, tenants: list[str]):
        return self.request("POST", f"/v1/schema/{class_name}/tenants",
                            body=[{"name": t} for t in tenants])

    def get_tenants(self, class_name: str) -> list[dict]:
        return self.request("GET", f"/v1/schema/{class_name}/tenants")

    # -- objects --------------------------------------------------------------

    def create_object(self, class_name: str, properties: dict, vector=None,
                      uuid: str | None = None, tenant: str | None = None) -> dict:
        body = {"class": class_name, "properties": properties}
        if vector is not None:
            body["vector"] = list(vector)
        if uuid is not None:
            body["id"] = uuid
        return self.request("POST", "/v1/objects",
                            params={"tenant": tenant} if tenant else None,
                            body=body)

    def get_object(self, class_name: str, uuid: str,
                   tenant: str | None = None,
                   consistency_level: str | None = None) -> dict:
        return self.request("GET", f"/v1/objects/{class_name}/{uuid}",
                            params={"tenant": tenant,
                                    "consistency_level": consistency_level})

    def delete_object(self, class_name: str, uuid: str,
                      tenant: str | None = None) -> None:
        self.request("DELETE", f"/v1/objects/{class_name}/{uuid}",
                     params={"tenant": tenant} if tenant else None)

    def patch_object(self, class_name: str, uuid: str, properties: dict) -> dict:
        return self.request("PATCH", f"/v1/objects/{class_name}/{uuid}",
                            body={"properties": properties})

    def list_objects(self, class_name: str, limit: int = 25, offset: int = 0,
                     after: str | None = None, sort: str | None = None,
                     order: str | None = None, where: dict | None = None,
                     tenant: str | None = None) -> dict:
        return self.request("GET", "/v1/objects", params={
            "class": class_name, "limit": limit, "offset": offset,
            "after": after, "sort": sort, "order": order,
            "where": json.dumps(where) if where else None, "tenant": tenant})

    def batch_objects(self, objects: list[dict]) -> list[dict]:
        return self.request("POST", "/v1/batch/objects",
                            body={"objects": objects})

    # -- graphql --------------------------------------------------------------

    def graphql(self, query: str, variables: dict | None = None) -> dict:
        return self.request("POST", "/v1/graphql",
                            body={"query": query,
                                  "variables": variables or {}})
