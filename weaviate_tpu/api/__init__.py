"""Public API layer: REST (/v1), gRPC, GraphQL.

Reference: adapters/handlers/{rest,graphql,grpc}.
"""

from weaviate_tpu.api.rest import RestServer

__all__ = ["RestServer"]
