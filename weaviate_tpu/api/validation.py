"""Declarative request-body validation for the REST /v1 surface.

Reference: go-swagger validates every body against the OpenAPI spec
(adapters/handlers/rest/embedded_spec.go) and answers 422 with structured
errors before any handler runs. This is the hand-rolled equivalent for the
write payloads: a compact spec language (required fields, typed fields,
nested specs) that produces the same shaped failures — field path + what
was expected — instead of handler-level 500s or silent coercion.
"""

from __future__ import annotations

import uuid as uuid_mod


def _type_name(spec) -> str:
    return {
        "str": "string", "num": "number", "int": "integer",
        "bool": "boolean", "dict": "object", "uuid": "uuid string",
        "vector": "number array", "strlist": "string array",
    }.get(spec, str(spec))


def _check(value, spec, path: str, errors: list[str]):
    if spec == "str":
        if not isinstance(value, str):
            errors.append(f"{path} must be a string")
    elif spec == "num":
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            errors.append(f"{path} must be a number")
    elif spec == "int":
        if isinstance(value, bool) or not isinstance(value, int):
            errors.append(f"{path} must be an integer")
    elif spec == "bool":
        if not isinstance(value, bool):
            errors.append(f"{path} must be a boolean")
    elif spec == "dict":
        if not isinstance(value, dict):
            errors.append(f"{path} must be an object")
    elif spec == "uuid":
        if not isinstance(value, str):
            errors.append(f"{path} must be a uuid string")
        else:
            try:
                uuid_mod.UUID(value)
            except ValueError:
                errors.append(f"{path} is not a valid uuid")
    elif spec == "vector":
        if not isinstance(value, list) or any(
                isinstance(v, bool) or not isinstance(v, (int, float))
                for v in value):
            errors.append(f"{path} must be a number array")
    elif spec == "strlist":
        if not isinstance(value, list) or any(
                not isinstance(v, str) for v in value):
            errors.append(f"{path} must be a string array")
    elif spec == "str_or_strlist":
        if not (isinstance(value, str) or (
                isinstance(value, list)
                and all(isinstance(v, str) for v in value))):
            errors.append(f"{path} must be a string or string array")
    elif isinstance(spec, dict):
        _check_obj(value, spec, path, errors)
    elif isinstance(spec, list):  # homogeneous list of sub-spec
        if not isinstance(value, list):
            errors.append(f"{path} must be an array")
        else:
            for i, v in enumerate(value):
                _check(v, spec[0], f"{path}[{i}]", errors)


def _check_obj(value, spec: dict, path: str, errors: list[str]):
    if not isinstance(value, dict):
        errors.append(f"{path} must be an object")
        return
    for name in spec.get("required", ()):
        # "a|b" = alternatives (the surface accepts lenient aliases,
        # e.g. class/name, dataType/data_type)
        alts = name.split("|")
        if all(value.get(a) in (None, "") for a in alts):
            errors.append(f"{path}.{alts[0]} is required")
    for name, sub in spec.get("fields", {}).items():
        if name in value and value[name] is not None:
            _check(value[name], sub, f"{path}.{name}", errors)


OBJECT = {
    "required": (),
    "fields": {
        "class": "str",
        "collection": "str",
        "id": "uuid",
        "properties": "dict",
        "vector": "vector",
        "vectors": "dict",
        "tenant": "str",
    },
}

BATCH_OBJECTS = {
    "fields": {
        "objects": [OBJECT],
        "fields": "strlist",
    },
}

SCHEMA_CLASS = {
    "required": ("class|name",),
    "fields": {
        "class": "str",
        "name": "str",
        "description": "str",
        "vectorizer": "str",
        "vectorIndexType": "str",
        "vectorIndexConfig": "dict",
        "invertedIndexConfig": "dict",
        "replicationConfig": "dict",
        "shardingConfig": "dict",
        "multiTenancyConfig": "dict",
        "moduleConfig": "dict",
        "properties": [{
            "required": ("name", "dataType|data_type|dataTypes"),
            "fields": {
                "name": "str",
                "dataType": "str_or_strlist",
                "data_type": "str_or_strlist",
                "description": "str",
                "tokenization": "str",
                "indexFilterable": "bool",
                "indexSearchable": "bool",
            },
        }],
    },
}

REFERENCE = {
    "required": ("beacon",),
    "fields": {"beacon": "str"},
}

CLASSIFICATION = {
    "required": ("class", "classifyProperties"),
    "fields": {
        "class": "str",
        "classifyProperties": "strlist",
        "basedOnProperties": "strlist",
        "type": "str",
        "settings": "dict",
    },
}

BACKUP = {
    "required": ("id",),
    "fields": {"id": "str", "include": "strlist", "exclude": "strlist",
               "config": "dict"},
}


def validate_body(spec: dict, body, what: str = "body") -> None:
    """Raise ValueError (REST maps it to 422) listing EVERY structural
    problem, not just the first — the reference's swagger errors do the
    same."""
    errors: list[str] = []
    _check_obj(body, spec, what, errors)
    if errors:
        raise ValueError("; ".join(errors))
