"""``python -m weaviate_tpu`` — start the server (cmd/weaviate-server)."""

from weaviate_tpu.server import main

main()
