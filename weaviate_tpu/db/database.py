"""Database facade: schema manager + collections.

Reference: adapters/repos/db/repo.go (DB struct :41) + usecases/schema
(handler.go:102 validation, manager). Schema is persisted in its own KV
bucket; on a cluster this layer sits behind the Raft FSM (cluster/store.go)
— single-node mode applies changes directly through the same interface the
Raft executor uses.
"""

from __future__ import annotations

import os
import threading

from weaviate_tpu.db.collection import Collection
from weaviate_tpu.db.sharding import ShardingState
from weaviate_tpu.schema.config import CollectionConfig, Property
from weaviate_tpu.storage.kv import KVStore


class Database:
    def __init__(self, data_dir: str = "./data", mesh=None,
                 local_node: str = "node-0", start_cycles: bool = False,
                 maintenance_interval: float = 5.0,
                 memory_monitor=None, remote=None, nodes_provider=None,
                 async_indexing: bool | None = None,
                 sync_wal: bool | None = None):
        self.data_dir = data_dir
        self.mesh = mesh
        # incident flight-recorder snapshots (tailboard) follow the data
        # dir of the most recently opened database — embedded/test use
        # gets on-disk snapshots without Server wiring
        from weaviate_tpu.runtime import driftwatch, tailboard

        tailboard.set_data_dir(data_dir)
        driftwatch.set_data_dir(data_dir)
        # host-count hint for scrape-time hbm_host_bytes refreshes
        from weaviate_tpu.parallel.mesh import host_count
        from weaviate_tpu.runtime.hbm_ledger import ledger as _hbm_ledger

        _hbm_ledger.set_host_count(host_count(mesh))
        self.local_node = local_node
        self.remote = remote
        self.async_indexing = async_indexing  # None = env decides per shard
        # PERSISTENCE_WAL_SYNC (ServerConfig.wal_sync): fsync acked
        # writes. None = read the env through config._flag (the ONE
        # parser, so embedded and server-launched use cannot disagree);
        # the schema store follows the same setting (raft's bucket pins
        # sync separately).
        if sync_wal is None:
            from weaviate_tpu.config import _flag

            sync_wal = _flag(os.environ, "PERSISTENCE_WAL_SYNC")
        self.sync_wal = sync_wal
        self.nodes_provider = nodes_provider or (lambda: [local_node])
        # node -> gossiped HBM ledger bytes; set by ClusterNode (reads
        # membership meta). Collections bind _node_hbm lazily so a hook
        # installed after startup still reaches already-loaded
        # collections' placement + cross-node migration decisions.
        self.node_hbm_provider = None
        # cluster hook fn(collection, [tenant]): routes auto tenant
        # creation through Raft (set by ClusterNode); None = local apply
        self.auto_tenant_hook = None
        # FROZEN-tier offload target (a backup backend); set by the server
        # when modules are configured (set_offload_backend)
        self.offload_backend = None
        os.makedirs(data_dir, exist_ok=True)
        self._lock = threading.RLock()
        self._schema_store = KVStore(os.path.join(data_dir, "_schema"),
                                     sync_wal=self.sync_wal)
        self._schema = self._schema_store.bucket("classes", "replace")
        self.collections: dict[str, Collection] = {}
        from weaviate_tpu.runtime import CycleManager, MemoryMonitor

        self.memwatch = memory_monitor or MemoryMonitor()
        # background maintenance (reference: cyclemanager drives LSM
        # flush/compaction); off by default so embedded/test use stays
        # deterministic — the server entry point enables it
        self.cycles = CycleManager()
        self.cycles.register("lsm-maintenance", self._maintenance_cycle,
                             maintenance_interval)
        # epoch policy (ROADMAP item 3): seal/compact/drop device epochs
        # (deletes reclaim HBM — what relieves the device-global
        # watermark) and, at a shard's per-shard quota watermark,
        # migrate its coldest sealed epoch to a sibling with headroom
        # instead of letting the quota 507 writes
        self.cycles.register("epoch-maintenance", self._epoch_cycle,
                             maintenance_interval)
        # driftwatch (ROADMAP item 1c): canary probes through the real
        # batcher + live-telemetry classification against benchkeeper
        # bands, on its own (longer) period — run_now("driftwatch") is
        # the deterministic test entry
        self.cycles.register("driftwatch", driftwatch.run_cycle,
                             driftwatch.interval_s())
        if start_cycles:
            self.cycles.start()
        self._load_existing()

    def _maintenance_cycle(self) -> bool:
        did = False
        for col in list(self.collections.values()):
            for shard in list(col.shards.values()):
                did = shard.maintenance() or did
        return did

    def _epoch_cycle(self) -> bool:
        did = False
        for col in list(self.collections.values()):
            did = col.epoch_maintenance() or did
        return did

    def _node_hbm(self) -> dict:
        """Late-binding wrapper: collections constructed before the
        cluster layer installs ``node_hbm_provider`` still see it."""
        if self.node_hbm_provider is None:
            return {}
        return self.node_hbm_provider()

    def _load_existing(self):
        for key in self._schema.keys():
            d = self._schema.get(key)
            cfg = CollectionConfig.from_dict(d["config"])
            state = ShardingState.from_dict(d["sharding"])
            col = Collection(
                self.data_dir, cfg, sharding_state=state, mesh=self.mesh,
                local_node=self.local_node, on_sharding_change=self._persist,
                memwatch=self.memwatch, remote=self.remote,
                nodes_provider=self.nodes_provider,
                async_indexing=self.async_indexing,
                sync_wal=self.sync_wal,
                node_hbm_provider=self._node_hbm,
            )
            col._auto_tenant_hook = self.auto_tenant_hook
            col.offload_backend = self.offload_backend
            self.collections[cfg.name] = col

    # -- schema ops (the Raft FSM op set, cluster/store_apply.go:133-160) ----

    def create_collection(self, config: CollectionConfig,
                          sharding_state=None) -> Collection:
        """``sharding_state`` is provided when the placement was computed
        elsewhere (the Raft proposer computes it once so every node
        applies an identical placement — reference: GetPartitions runs in
        the schema handler BEFORE the Raft submit)."""
        config.validate()
        with self._lock:
            if config.name in self.collections:
                raise ValueError(f"collection {config.name!r} already exists")
            col = Collection(self.data_dir, config,
                             sharding_state=sharding_state, mesh=self.mesh,
                             local_node=self.local_node,
                             on_sharding_change=self._persist,
                             memwatch=self.memwatch, remote=self.remote,
                             nodes_provider=self.nodes_provider,
                             async_indexing=self.async_indexing,
                             sync_wal=self.sync_wal,
                             node_hbm_provider=self._node_hbm)
            col._auto_tenant_hook = self.auto_tenant_hook
            col.offload_backend = self.offload_backend
            self.collections[config.name] = col
            self._persist(col)
            return col

    def set_offload_backend(self, backend) -> None:
        """Backup backend receiving FROZEN tenants (reference: offload
        modules, OFFLOAD_* env). Propagates to every collection."""
        self.offload_backend = backend
        for col in self.collections.values():
            col.offload_backend = backend

    def set_auto_tenant_hook(self, hook) -> None:
        with self._lock:
            self.auto_tenant_hook = hook
            for col in self.collections.values():
                col._auto_tenant_hook = hook

    def delete_collection(self, name: str) -> bool:
        with self._lock:
            col = self.collections.pop(name, None)
            if col is None:
                return False
            col.close()
            self._schema.delete(name.encode())
            import shutil

            # exact-case path (matches Shard dir layout): names differing
            # only in case are distinct collections
            shutil.rmtree(os.path.join(self.data_dir, name),
                          ignore_errors=True)
            return True

    def add_property(self, collection: str, prop: Property):
        """Schema evolution (reference: ADD_PROPERTY FSM op; auto-schema
        uses this too)."""
        with self._lock:
            col = self.get_collection(collection)
            prop.validate()
            # case-insensitive duplicate check, matching
            # CollectionConfig.validate() — a case-variant duplicate would
            # persist fine but make the schema unloadable on restart
            if any(p.name.lower() == prop.name.lower()
                   for p in col.config.properties):
                raise ValueError(f"property {prop.name!r} already exists")
            col.config.properties.append(prop)
            self._persist(col)

    # mutable-at-runtime config surface (reference: UpdateUserConfig /
    # update-class validation — vectorizer, index type, sharding and
    # multi-tenancy are immutable after creation)
    def validate_collection_update(self, new_cfg: CollectionConfig) -> None:
        """Immutability checks only — NO mutation (the cluster path
        validates first, then replicates through Raft; applying before a
        successful propose would diverge this node from its peers)."""
        cur = self.get_collection(new_cfg.name).config
        for vc_new in new_cfg.vectors:
            vc_cur = cur.vector_config(vc_new.name)
            if vc_cur is None:
                raise ValueError(
                    f"cannot add vector space {vc_new.name!r} via update")
            if vc_new.vectorizer != vc_cur.vectorizer:
                raise ValueError("vectorizer is immutable")
            if vc_new.index.index_type != vc_cur.index.index_type:
                raise ValueError("vectorIndexType is immutable")
            if vc_new.index.metric != vc_cur.index.metric:
                raise ValueError("distance metric is immutable")
            if (vc_cur.index.quantization
                    and vc_new.index.quantization != vc_cur.index.quantization):
                # enabling is a one-way door (reference config_update.go:
                # compression can be turned ON via update, never off)
                raise ValueError("quantization cannot be disabled or "
                                 "changed once enabled")
            if vc_new.index.quantization and not vc_cur.index.quantization:
                # compatibility gate BEFORE anything persists — a config
                # that compress() would reject must not commit (it would
                # wedge every later update behind the one-way-door check)
                itype = vc_cur.index.index_type
                if itype in ("hnsw", "ivf") and \
                        vc_new.index.quantization != "pq":
                    raise ValueError(
                        f"{itype} supports runtime quantization='pq' only")
                if itype == "hnsw" and vc_cur.index.metric not in (
                        "l2-squared", "dot", "cosine", "cosine-dot"):
                    raise ValueError(
                        f"no ADC form for metric {vc_cur.index.metric!r}")
        if new_cfg.sharding.desired_count != cur.sharding.desired_count:
            raise ValueError("shard count is immutable (resharding "
                             "is not supported)")
        if new_cfg.multi_tenancy.enabled != cur.multi_tenancy.enabled:
            raise ValueError("multiTenancy.enabled is immutable")

    def update_collection(self, new_cfg: CollectionConfig,
                          allow_scale: bool = True) -> None:
        """``allow_scale=False`` is the Raft-FSM apply path: factor changes
        are IGNORED there (they only ever commit via the deterministic
        "update_sharding" op) — running the Scaler inside FSM apply would
        make log application network-dependent and non-deterministic
        across nodes."""
        with self._lock:
            self.validate_collection_update(new_cfg)
            cur = self.get_collection(new_cfg.name).config
            if allow_scale and \
                    new_cfg.replication.factor != cur.replication.factor:
                # Factor changes move shard data (reference routes them
                # through usecases/scaler) — recording the new number
                # without copying would leave phantom replicas that hold
                # nothing, so reads routed there miss data.
                from weaviate_tpu.cluster.scaler import Scaler

                Scaler(self).scale(new_cfg.name,
                                   new_cfg.replication.factor)

            def apply(cfg):
                cfg.description = new_cfg.description
                cfg.inverted = new_cfg.inverted
                cfg.module_config = new_cfg.module_config
                cfg.multi_tenancy.auto_tenant_creation = \
                    new_cfg.multi_tenancy.auto_tenant_creation
                cfg.multi_tenancy.auto_tenant_activation = \
                    new_cfg.multi_tenancy.auto_tenant_activation
                for vc_new in new_cfg.vectors:
                    vc = cfg.vector_config(vc_new.name)
                    # runtime-tunable index knobs (reference:
                    # hnsw/config_update.go — ef, rescore, thresholds)
                    vc.index.ef = vc_new.index.ef
                    vc.index.ef_construction = vc_new.index.ef_construction
                    vc.index.rescore_limit = vc_new.index.rescore_limit
                    vc.index.flat_to_ann_threshold = \
                        vc_new.index.flat_to_ann_threshold
                    vc.index.ivf_nprobe = vc_new.index.ivf_nprobe
                    if vc_new.index.quantization and \
                            not vc.index.quantization:
                        # runtime compression enable (compress.go:38 via
                        # config_update.go) — applied to live indexes in
                        # apply_runtime_config
                        vc.index.quantization = vc_new.index.quantization
                        vc.index.pq_segments = vc_new.index.pq_segments
                        vc.index.pq_centroids = vc_new.index.pq_centroids
                    vc.module_config = vc_new.module_config

            self.update_collection_config(new_cfg.name, apply)
            # push runtime knobs into LIVE objects — they copied config
            # values at construction and would otherwise only pick the
            # update up after a restart
            self.get_collection(new_cfg.name).apply_runtime_config()

    def update_collection_config(self, name: str, mutate) -> None:
        """Runtime-mutable config path (reference: UpdateUserConfig,
        vector_index.go:33). ``mutate(config)`` edits in place; validation
        runs on a copy so a rejected update leaves the live config intact."""
        import copy

        with self._lock:
            col = self.get_collection(name)
            candidate = copy.deepcopy(col.config)
            mutate(candidate)
            candidate.validate()
            mutate(col.config)
            self._persist(col)

    def _persist(self, col: Collection):
        self._schema.put(
            col.config.name.encode(),
            {"config": col.config.to_dict(), "sharding": col.sharding.to_dict()},
        )

    def get_collection(self, name: str) -> Collection:
        col = self.collections.get(name)
        if col is None:
            raise KeyError(f"collection {name!r} does not exist")
        return col

    def list_collections(self) -> list[str]:
        return sorted(self.collections)

    def schema_dict(self) -> dict:
        return {name: col.config.to_dict()
                for name, col in sorted(self.collections.items())}

    # -- tenants -------------------------------------------------------------

    def add_tenants(self, collection: str, tenants: list[str]):
        col = self.get_collection(collection)
        for t in tenants:
            col.add_tenant(t)
        with self._lock:
            self._persist(col)

    def update_tenant_status(self, collection: str,
                             tenants: list[dict]) -> None:
        """[{name, activityStatus}] — HOT/COLD offload (reference: PUT
        tenants)."""
        col = self.get_collection(collection)
        for t in tenants:
            col.set_tenant_status(t["name"],
                                  t.get("activityStatus", "HOT"))
        with self._lock:
            self._persist(col)

    def remove_tenants(self, collection: str, tenants: list[str]):
        col = self.get_collection(collection)
        for t in tenants:
            col.remove_tenant(t)
        with self._lock:
            self._persist(col)

    # -- lifecycle -----------------------------------------------------------

    def flush(self):
        for col in self.collections.values():
            col.flush()

    def close(self):
        self.cycles.stop()
        with self._lock:
            for col in self.collections.values():
                col.close()
            self.collections.clear()
            self._schema_store.close()
